"""Headline benchmark: GPT-2-125M SPMD training throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference publishes no in-repo number for its north-star config
("Ray Train GPT-2 DDP tokens/sec/chip", BASELINE.md "Gaps" section). We use
the public NCCL/A100 equivalent — GPT-2-124M torch DDP on A100-40GB sustains
~60k tokens/s/GPU (nanoGPT-class training, bf16, flash attention) — as the
per-chip baseline the north star asks us to match on TPU.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

BASELINE_TOKENS_PER_SEC_PER_CHIP = 60_000.0


def main():
    from ray_tpu.models import gpt2
    from ray_tpu.parallel.mesh import MeshConfig, build_mesh
    from ray_tpu.train.spmd import compile_gpt2_train, default_optimizer

    devices = jax.devices()
    n = len(devices)
    mesh = build_mesh(MeshConfig(dp=n), devices=devices)

    import os

    preset = os.environ.get("BENCH_PRESET", "gpt2-125m")
    seq_len = int(os.environ.get("BENCH_SEQ", "1024"))
    # defaults per preset from the 2026-07 sweeps (benchmarks/MFU_ANALYSIS.md
    # + r4 350M sweep): dots-remat @ 24 is the best 125M config the relay
    # will compile (it rejects batch >= 40; remat=False and dots_all
    # OOM/underperform; flash loses to XLA's fused dense attention at 1024)
    default_batch = {"gpt2-125m": 24, "gpt2-350m": 14,
                     "gpt2-774m": 4, "gpt2-1.5b": 2}.get(preset, 8)
    per_chip_batch = int(os.environ.get("BENCH_BATCH", str(default_batch)))
    batch = per_chip_batch * n
    cfg = gpt2.GPT2Config.preset(
        preset, max_seq_len=seq_len,
        remat=os.environ.get("BENCH_REMAT", "1") != "0",
        remat_policy=os.environ.get("BENCH_REMAT_POLICY", "dots"),
        attn_impl=os.environ.get("BENCH_ATTN", "auto"),
        ce_chunk=int(os.environ.get("BENCH_CE_CHUNK", "0")))

    train = compile_gpt2_train(cfg, mesh, optimizer=default_optimizer(total_steps=100))
    state = train.init_fn(jax.random.key(0))

    rng = np.random.default_rng(0)
    tokens = jax.device_put(
        rng.integers(0, cfg.vocab_size, (batch, seq_len + 1), dtype=np.int32),
        train.batch_sharding)
    data = {"tokens": tokens}

    # warmup / compile
    for _ in range(3):
        state, metrics = train.step_fn(state, data)
    float(metrics["loss"])

    # time-to-fetch: the remote-TPU relay's block_until_ready can return
    # before execution completes, so a host fetch of the chain's final
    # scalar is the only honest completion barrier
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = train.step_fn(state, data)
    loss_val = float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq_len
    tps_per_chip = tokens_per_step * iters / dt / n
    mfu = (gpt2.flops_per_token(cfg, seq_len) * tps_per_chip) / 197e12  # v5e bf16 peak

    print(json.dumps({
        "metric": f"{preset.replace('-', '_').replace('.', '_')}"
                  f"_train_tokens_per_sec_per_chip",
        "value": round(tps_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tps_per_chip / BASELINE_TOKENS_PER_SEC_PER_CHIP, 3)
        if preset == "gpt2-125m" else None,
        "extra": {"n_chips": n, "seq_len": seq_len, "per_chip_batch": per_chip_batch,
                  "preset": preset,
                  "step_ms": round(dt / iters * 1e3, 2), "approx_mfu": round(mfu, 3),
                  "loss": loss_val},
    }))


if __name__ == "__main__":
    main()
