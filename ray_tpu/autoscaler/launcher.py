"""Cluster launcher: `ray-tpu up/down/exec/attach cluster.yaml`.

Parity: `python/ray/autoscaler/_private/commands.py` (`ray up/down/exec/
attach/rsync`) — bring a whole cluster up from one YAML, over the
command-runner seam (SSH for real fleets, local subshells for
single-machine and CI).

Config schema (reference cluster.yaml, trimmed to this runtime):

```yaml
cluster_name: demo
provider:
  type: ssh            # or "local"
auth:
  ssh_user: ubuntu
  ssh_private_key: ~/.ssh/id_rsa
head_node:
  host: 10.0.0.1
  port: 7777           # optional fixed head port
  num_cpus: 8          # optional resource overrides
worker_nodes:
  - host: 10.0.0.2
    num_cpus: 16
  - host: 10.0.0.3
worker_node_types:     # optional: autoscaler node types (SSHNodeProvider)
  default:
    resources: {CPU: 16}
    max_nodes: 2
setup_commands:        # run on every node before start
  - pip install -e /opt/ray_tpu
file_mounts:           # target: source, rsync'd to every node
  /opt/app: ./app
env:                   # exported for start commands
  JAX_PLATFORMS: cpu
```

State: `<STATE_DIR>/clusters/<name>.json` records the head address and
started nodes, so `down`/`exec`/`attach` work without re-reading hosts.
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.command_runner import (CommandRunner,
                                               LocalCommandRunner,
                                               make_runner)
from ray_tpu.utils.platform import STATE_DIR

CLUSTER_DIR = os.path.join(STATE_DIR, "clusters")


def load_config(path: str) -> dict:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    cfg.setdefault("cluster_name", "default")
    cfg.setdefault("provider", {"type": "local"})
    cfg.setdefault("auth", {})
    cfg.setdefault("head_node", {"host": "localhost"})
    cfg.setdefault("worker_nodes", [])
    cfg.setdefault("setup_commands", [])
    cfg.setdefault("file_mounts", {})
    cfg.setdefault("env", {})
    return cfg


def _state_path(name: str) -> str:
    os.makedirs(CLUSTER_DIR, exist_ok=True)
    return os.path.join(CLUSTER_DIR, f"{name}.json")


def _save_state(name: str, state: dict) -> None:
    tmp = _state_path(name) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1)
    os.replace(tmp, _state_path(name))


def load_state(name: str) -> Optional[dict]:
    try:
        with open(_state_path(name)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _python(cfg: dict) -> str:
    return cfg.get("python", sys.executable)


def _prepare_node(cfg: dict, node: dict, runner: CommandRunner,
                  log) -> None:
    for target, source in cfg["file_mounts"].items():
        log(f"  rsync {source} -> {node.get('host')}:{target}")
        runner.rsync_up(source, target)
    for cmd in cfg["setup_commands"]:
        log(f"  setup: {cmd}")
        rc, out = runner.run(cmd, timeout=600, env=cfg["env"])
        if rc != 0:
            raise RuntimeError(f"setup command failed on "
                               f"{node.get('host')}: {cmd}\n{out}")


def _start_flags(node: dict) -> str:
    flags = ""
    if node.get("num_cpus") is not None:
        flags += f" --num-cpus {node['num_cpus']}"
    if node.get("num_tpu_chips") is not None:
        flags += f" --num-tpu-chips {node['num_tpu_chips']}"
    if node.get("resources"):
        flags += f" --resources {shlex.quote(json.dumps(node['resources']))}"
    if node.get("labels"):
        flags += f" --labels {shlex.quote(json.dumps(node['labels']))}"
    return flags


def _make_gcp_provider(cfg: dict, head_address: str = ""):
    """GCPNodeProvider from a cluster.yaml whose `provider.type == gcp`.
    The head VM is node type `head` (spec from head_node.gcp)."""
    from ray_tpu.autoscaler import gcp as gcp_mod

    node_types = dict(cfg.get("worker_node_types", {}))
    node_types["head"] = {
        "resources": cfg["head_node"].get("resources", {}),
        "gcp": cfg["head_node"].get("gcp", {}),
        "max_nodes": 1,
    }
    return gcp_mod.GCPNodeProvider(
        node_types, head_address, auth=cfg["auth"], python=_python(cfg),
        project=cfg["provider"]["project"],
        zone=cfg["provider"].get("zone")
        or cfg["provider"].get("availability_zone"),
        cluster_name=cfg["cluster_name"],
        api=gcp_mod.api_from_config(cfg["provider"]),
        use_internal_ips=cfg["provider"].get("use_internal_ips", False))


def up(cfg: dict, log=print) -> dict:
    """Bring the cluster up: head first, then every worker joins it.
    Returns the saved state dict (head address etc.).

    With `provider.type: gcp` the head VM (and `min_workers` workers per
    `worker_node_types` entry) are CREATED on GCP first (reference
    `ray up` + GCPNodeProvider); otherwise nodes are pre-existing hosts
    reached over SSH."""
    name = cfg["cluster_name"]
    head = cfg["head_node"]
    provider = None
    gcp_instances: List[dict] = []
    if cfg["provider"].get("type") == "gcp":
        provider = _make_gcp_provider(cfg)
        log(f"[{name}] creating head VM on GCP "
            f"({cfg['provider']['project']})")
        head_name, head_hosts = provider.create_raw_instance("head")
        gcp_instances.append({"name": head_name,
                              "is_tpu": provider._is_tpu("head")})
        head = {**head, "host": head_hosts[0]["host"]}
        log(f"[{name}] head VM {head_name} at {head['host']}")
    head_runner = make_runner(head, cfg["auth"])
    log(f"[{name}] preparing head {head.get('host', 'localhost')}")
    _prepare_node(cfg, head, head_runner, log)
    port = head.get("port", 0)
    cli = f"{_python(cfg)} -m ray_tpu.scripts.cli"
    log(f"[{name}] starting head")
    rc, out = head_runner.run(
        f"{cli} start --head --port {port}{_start_flags(head)}",
        timeout=120, env=cfg["env"])
    if rc != 0:
        raise RuntimeError(f"head start failed:\n{out}")
    addr, head_pid = None, None
    for line in out.splitlines():
        if line.startswith("started head at "):
            rest = line.split("started head at ", 1)[1].strip()
            addr = rest.split(" ", 1)[0]
            if "(pid " in rest:
                head_pid = int(rest.split("(pid ", 1)[1].rstrip(")"))
    if addr is None:
        raise RuntimeError(f"could not parse head address from:\n{out}")
    # the address the WORKERS use: the head host's reachable name
    host = head.get("host", "localhost")
    join_addr = addr if host in ("localhost", "127.0.0.1", "local") else \
        f"{host}:{addr.rsplit(':', 1)[1]}"
    state = {"cluster_name": name, "head": head, "head_pid": head_pid,
             "address": join_addr,
             "auth": cfg["auth"], "workers": [], "env": cfg["env"],
             "python": _python(cfg)}
    if provider is not None:
        provider.head_address = join_addr
        # min_workers per node type come up with the cluster (reference
        # available_node_types[...].min_workers); further scale-up is the
        # autoscaler's job against the same provider
        for t, spec in cfg.get("worker_node_types", {}).items():
            for i in range(int(spec.get("min_workers", 0))):
                log(f"[{name}] creating {t} worker {i} on GCP")
                pid = provider.create_node(t)
                entry = provider.wait_ready(
                    pid, timeout=cfg["provider"].get(
                        "create_timeout_s", 600))
                gcp_instances.append({"name": entry["name"],
                                      "is_tpu": entry["is_tpu"]})
                state["workers"].append(
                    {"provider_id": pid, "hosts": entry["hosts"],
                     "host": entry["hosts"][0]["host"]})
        state["provider"] = {**cfg["provider"], "instances": gcp_instances}
    _save_state(name, state)
    for node in cfg["worker_nodes"]:
        runner = make_runner(node, cfg["auth"])
        log(f"[{name}] preparing worker {node.get('host', 'localhost')}")
        _prepare_node(cfg, node, runner, log)
        rc, out = runner.run(
            f"{cli} start --address {join_addr}{_start_flags(node)}",
            timeout=120, env=cfg["env"])
        if rc != 0:
            raise RuntimeError(f"worker start failed on "
                               f"{node.get('host')}:\n{out}")
        node = dict(node)
        node["pid"] = parse_daemon_pid(out)
        state["workers"].append(node)
        _save_state(name, state)
    log(f"[{name}] up: head at {join_addr}, "
        f"{len(state['workers'])} worker node(s)")
    return state


def parse_daemon_pid(out: str) -> Optional[int]:
    for line in out.splitlines():
        if line.startswith("node daemon started (pid "):
            return int(line.split("(pid ", 1)[1].split(")", 1)[0])
    return None


def down(name_or_cfg, log=print) -> None:
    """Stop every node recorded in the cluster state (reference
    `ray down`). Kills the RECORDED pids, not every ray-tpu process on
    the machine — co-located clusters (and the test harness) survive."""
    state = name_or_cfg if isinstance(name_or_cfg, dict) else \
        load_state(name_or_cfg)
    if state is None:
        raise RuntimeError(f"no cluster state for {name_or_cfg!r}; "
                           f"was it started with `ray-tpu up`?")
    name = state["cluster_name"]
    if state.get("provider", {}).get("type") == "gcp":
        # deleting the VMs IS the teardown (reference `ray down` via
        # GCPNodeProvider.terminate_node)
        from ray_tpu.autoscaler import gcp as gcp_mod

        api = gcp_mod.api_from_config(state["provider"])
        for inst in state["provider"].get("instances", []):
            log(f"[{name}] deleting GCP instance {inst['name']}")
            try:
                if inst.get("is_tpu"):
                    api.delete_tpu_node(inst["name"])
                else:
                    api.delete_instance(inst["name"])
            except Exception as e:
                log(f"  delete failed (continuing): {e!r}")
        try:
            os.unlink(_state_path(name))
        except OSError:
            pass
        log(f"[{name}] down")
        return
    for node in state["workers"]:
        runner = make_runner(node, state.get("auth", {}))
        log(f"[{name}] stopping worker {node.get('host', 'localhost')}")
        try:
            if node.get("pid"):
                runner.run(f"kill {node['pid']} 2>/dev/null || true",
                           timeout=30)
        except Exception as e:
            log(f"  stop failed (continuing): {e!r}")
    runner = make_runner(state["head"], state.get("auth", {}))
    log(f"[{name}] stopping head {state['head'].get('host', 'localhost')}")
    try:
        if state.get("head_pid"):
            # SIGTERM → head.stop() pushes shutdown_node to every daemon
            runner.run(f"kill {state['head_pid']} 2>/dev/null || true",
                       timeout=30)
        else:
            cli = (f"{state.get('python', sys.executable)} "
                   f"-m ray_tpu.scripts.cli")
            runner.run(f"{cli} stop", timeout=60)
    except Exception as e:
        log(f"  stop failed (continuing): {e!r}")
    try:
        os.unlink(_state_path(name))
    except OSError:
        pass
    log(f"[{name}] down")


def exec_cmd(name: str, cmd: str, on: str = "head") -> int:
    """Run a shell command on a cluster node (reference `ray exec`).
    RAY_TPU_ADDRESS is exported so `python my_driver.py` just works."""
    state = load_state(name)
    if state is None:
        raise RuntimeError(f"no cluster state for {name!r}")
    node = state["head"] if on == "head" else state["workers"][int(on)]
    runner = make_runner(node, state.get("auth", {}))
    env = dict(state.get("env", {}))
    env["RAY_TPU_ADDRESS"] = state["address"]
    rc, out = runner.run(cmd, env=env)
    if out:
        print(out, end="" if out.endswith("\n") else "\n")
    return rc


def attach_argv(name: str) -> List[str]:
    """argv for an interactive shell on the head (reference `ray attach`)."""
    state = load_state(name)
    if state is None:
        raise RuntimeError(f"no cluster state for {name!r}")
    runner = make_runner(state["head"], state.get("auth", {}))
    return runner.remote_shell_command()


def rsync(name: str, source: str, target: str, up_: bool = True) -> None:
    state = load_state(name)
    if state is None:
        raise RuntimeError(f"no cluster state for {name!r}")
    runner = make_runner(state["head"], state.get("auth", {}))
    if up_:
        runner.rsync_up(source, target)
    else:
        runner.rsync_down(source, target)
