"""Command runners: how the launcher/providers reach a node.

Parity: `python/ray/autoscaler/_private/command_runner.py`
(SSHCommandRunner / DockerCommandRunner). The seam every launcher and
provider operation goes through — tests swap in a recording mock, the
local provider a subshell, production SSH.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
from typing import List, Optional, Tuple

_SSH_OPTS = [
    "-o", "ConnectTimeout=10",
    "-o", "StrictHostKeyChecking=no",
    "-o", "UserKnownHostsFile=/dev/null",
    "-o", "LogLevel=ERROR",
    # multiplex connections like the reference (ControlMaster) so repeated
    # setup commands don't re-handshake
    "-o", "ControlMaster=auto",
    "-o", "ControlPersist=10s",
]


class CommandRunner:
    """One target node. `run` executes a shell command; `rsync_up/down`
    move files. Implementations must be safe to call from threads."""

    def run(self, cmd: str, timeout: Optional[float] = None,
            env: Optional[dict] = None) -> Tuple[int, str]:
        raise NotImplementedError

    def rsync_up(self, source: str, target: str) -> None:
        raise NotImplementedError

    def rsync_down(self, source: str, target: str) -> None:
        raise NotImplementedError

    def remote_shell_command(self) -> List[str]:
        """argv for an interactive shell (CLI `attach`)."""
        raise NotImplementedError


class LocalCommandRunner(CommandRunner):
    """Runs on THIS machine (single-host clusters, CI, and the head node
    when `ray-tpu up` executes on it directly)."""

    def run(self, cmd: str, timeout: Optional[float] = None,
            env: Optional[dict] = None) -> Tuple[int, str]:
        merged = {**os.environ, **(env or {})}
        proc = subprocess.run(["bash", "-c", cmd], capture_output=True,
                              text=True, timeout=timeout, env=merged)
        return proc.returncode, proc.stdout + proc.stderr

    def rsync_up(self, source: str, target: str) -> None:
        import shutil

        target_dir = (target.rstrip("/") if target.endswith("/")
                      else os.path.dirname(target)) or "."
        os.makedirs(target_dir, exist_ok=True)
        if shutil.which("rsync"):
            subprocess.run(["rsync", "-a", source, target], check=True)
        elif os.path.isdir(source):
            # minimal-image fallback: same trailing-slash semantics as
            # rsync -a (src/ copies CONTENTS, src copies the directory);
            # symlinks preserved as links, dangling ones included
            dst = target if source.endswith("/") else os.path.join(
                target, os.path.basename(source.rstrip("/")))
            shutil.copytree(source, dst, dirs_exist_ok=True, symlinks=True)
        else:
            shutil.copy2(source, target)

    rsync_down = rsync_up

    def remote_shell_command(self) -> List[str]:
        return ["bash"]


class SSHCommandRunner(CommandRunner):
    """Drives a remote node over ssh/rsync (reference SSHCommandRunner)."""

    def __init__(self, host: str, user: Optional[str] = None,
                 ssh_key: Optional[str] = None, port: int = 22):
        self.host = host
        self.user = user
        self.ssh_key = ssh_key
        self.port = port

    def _target(self) -> str:
        return f"{self.user}@{self.host}" if self.user else self.host

    def _ssh_base(self) -> List[str]:
        base = ["ssh", *_SSH_OPTS, "-p", str(self.port)]
        if self.ssh_key:
            base += ["-i", self.ssh_key]
        return base

    def run(self, cmd: str, timeout: Optional[float] = None,
            env: Optional[dict] = None) -> Tuple[int, str]:
        exports = "".join(
            f"export {k}={shlex.quote(str(v))}; " for k, v in (env or {}).items())
        argv = self._ssh_base() + [self._target(),
                                   f"bash -c {shlex.quote(exports + cmd)}"]
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout)
        return proc.returncode, proc.stdout + proc.stderr

    def _rsync(self, source: str, target: str) -> None:
        ssh_cmd = " ".join(self._ssh_base())
        subprocess.run(["rsync", "-az", "-e", ssh_cmd, source, target],
                       check=True)

    def rsync_up(self, source: str, target: str) -> None:
        self._rsync(source, f"{self._target()}:{target}")

    def rsync_down(self, source: str, target: str) -> None:
        self._rsync(f"{self._target()}:{source}", target)

    def remote_shell_command(self) -> List[str]:
        return self._ssh_base() + ["-tt", self._target()]


def make_runner(node_cfg: dict, auth: dict) -> CommandRunner:
    """`{"host": ...}` + auth → runner. host in (localhost, 127.0.0.1,
    "local") short-circuits to the local runner so single-machine configs
    and CI need no sshd."""
    host = node_cfg.get("host", "localhost")
    if host in ("localhost", "127.0.0.1", "local"):
        return LocalCommandRunner()
    return SSHCommandRunner(host, user=auth.get("ssh_user"),
                            ssh_key=auth.get("ssh_private_key"),
                            port=int(auth.get("ssh_port", 22)))
