"""Kubernetes node provider: worker pods on demand.

Behavioral parity with the reference's KubeRay-side scaling story
(`python/ray/autoscaler/_private/kuberay/node_provider.py` — the
autoscaler creates/deletes worker pods through the K8s API; the operator
reconciles): here the provider talks to the API server directly over its
REST surface, so an in-cluster head can grow/shrink its own worker fleet
with no operator installed.

- A worker "node" is ONE pod running `ray-tpu start --address <head>`;
  the pod's command joins the cluster, so no SSH/command-runner is
  involved (pods are cattle: terminate = DELETE).
- GKE TPU pods: set `tpu` resources in the node type (mapped to
  `google.com/tpu` requests/limits) plus any nodeSelector (e.g.
  `cloud.google.com/gke-tpu-topology`); the in-pod daemon self-labels
  from the GKE-injected TPU env (core/resources.py).
- All HTTP rides one injectable `request_fn(method, path, body)` seam —
  tests run against a fake in-process API server; production auth is the
  mounted service-account token.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import NodeProvider

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
LABEL_CLUSTER = "ray-tpu/cluster"
LABEL_NODE_TYPE = "ray-tpu/node-type"
LABEL_PROVIDER_ID = "ray-tpu/provider-id"


def default_request_fn(method: str, path: str,
                       body: Optional[dict]) -> Tuple[int, dict]:
    """In-cluster transport: API server from env, SA token auth."""
    import ssl
    import urllib.error
    import urllib.request

    host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    with open(os.path.join(SA_DIR, "token")) as f:
        token = f.read().strip()
    ctx = ssl.create_default_context(cafile=os.path.join(SA_DIR, "ca.crt"))
    req = urllib.request.Request(
        f"https://{host}:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        method=method,
        headers={"Authorization": f"Bearer {token}",
                 "Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30, context=ctx) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        try:
            return e.code, json.loads(payload)
        except (ValueError, TypeError):
            return e.code, {"error": payload.decode(errors="replace")}


class K8sApiError(RuntimeError):
    def __init__(self, status: int, body: dict):
        super().__init__(f"K8s API error {status}: {body}")
        self.status = status
        self.body = body


class K8sApi:
    def __init__(self, namespace: str = "default",
                 request_fn: Callable[..., Tuple[int, dict]] = None):
        self.namespace = namespace
        self.request_fn = request_fn or default_request_fn

    def _call(self, method: str, path: str, body: dict = None,
              ok_missing: bool = False) -> dict:
        status, payload = self.request_fn(method, path, body)
        if status == 404 and ok_missing:
            return {}
        if status >= 300:
            raise K8sApiError(status, payload)
        return payload

    @property
    def _pods(self) -> str:
        return f"/api/v1/namespaces/{self.namespace}/pods"

    def create_pod(self, manifest: dict) -> dict:
        return self._call("POST", self._pods, manifest)

    def get_pod(self, name: str) -> Optional[dict]:
        got = self._call("GET", f"{self._pods}/{name}", ok_missing=True)
        return got or None

    def delete_pod(self, name: str) -> dict:
        return self._call("DELETE", f"{self._pods}/{name}",
                          ok_missing=True)

    def list_pods(self, label_selector: str = "") -> List[dict]:
        path = self._pods
        if label_selector:
            from urllib.parse import quote

            path += f"?labelSelector={quote(label_selector)}"
        return self._call("GET", path).get("items", [])


class K8sNodeProvider(NodeProvider):
    """Node types gain a `k8s:` block:

    ```yaml
    worker_node_types:
      cpu_worker:
        max_nodes: 8
        resources: {CPU: 4}
        k8s:
          image: ray-tpu:latest
          cpu: "4"
          memory: 8Gi
      tpu_worker:
        max_nodes: 4
        resources: {TPU: 4}
        k8s:
          image: ray-tpu:latest
          tpu: "4"                  # -> google.com/tpu requests/limits
          node_selector:
            cloud.google.com/gke-tpu-accelerator: tpu-v5-lite-podslice
            cloud.google.com/gke-tpu-topology: 2x2
    ```
    """

    def __init__(self, node_types: Dict[str, dict], head_address: str,
                 *, namespace: str = "default",
                 cluster_name: str = "default",
                 api: Optional[K8sApi] = None):
        super().__init__(node_types)
        self.head_address = head_address
        self.cluster_name = cluster_name
        self.api = api or K8sApi(namespace)
        self._nodes: Dict[str, dict] = {}
        self._types: Dict[str, str] = {}
        self._counter = 0
        self._lock = threading.Lock()

    # ----------------------------------------------------------- manifest
    def _manifest(self, name: str, node_type: str) -> dict:
        spec = self.node_types[node_type]
        k8s = spec.get("k8s", {})
        labels = {**spec.get("labels", {}),
                  "ray_tpu.io/provider-node-id": name}
        args = ["start", "--address", self.head_address,
                "--labels", json.dumps(labels)]
        if spec.get("resources"):
            args += ["--resources", json.dumps(spec["resources"])]
        requests: Dict[str, str] = {}
        if k8s.get("cpu"):
            requests["cpu"] = str(k8s["cpu"])
        if k8s.get("memory"):
            requests["memory"] = str(k8s["memory"])
        if k8s.get("tpu"):
            requests["google.com/tpu"] = str(k8s["tpu"])
        container = {
            "name": "ray-tpu-worker",
            "image": k8s.get("image", "ray-tpu:latest"),
            "command": [k8s.get("python", "python"), "-m",
                        "ray_tpu.scripts.cli", *args, "--block"],
            "env": [{"name": k, "value": str(v)}
                    for k, v in k8s.get("env", {}).items()],
            "resources": {"requests": requests, "limits": dict(requests)},
        }
        pod_spec = {"restartPolicy": "Never", "containers": [container]}
        if k8s.get("node_selector"):
            pod_spec["nodeSelector"] = dict(k8s["node_selector"])
        return {"apiVersion": "v1", "kind": "Pod",
                "metadata": {
                    "name": name,
                    "labels": {LABEL_CLUSTER: self.cluster_name,
                               LABEL_NODE_TYPE: node_type,
                               LABEL_PROVIDER_ID: name}},
                "spec": pod_spec}

    # ----------------------------------------------------------- provider
    def create_node(self, node_type: str) -> str:
        with self._lock:
            self._counter += 1
            name = (f"{self.cluster_name}-{node_type}-{self._counter}"
                    .replace("_", "-").lower())
            self._nodes[name] = {"name": name, "node_type": node_type}
            self._types[name] = node_type
        try:
            self.api.create_pod(self._manifest(name, node_type))
        except Exception:
            with self._lock:
                self._nodes.pop(name, None)
                self._types.pop(name, None)
            raise
        return name

    def terminate_node(self, provider_id: str) -> None:
        with self._lock:
            if self._nodes.pop(provider_id, None) is None:
                return
            self._types.pop(provider_id, None)
        try:
            self.api.delete_pod(provider_id)
        except Exception:
            pass

    def non_terminated_nodes(self) -> List[str]:
        # reconcile with the API server: pods can die outside our control
        # (evictions, OOM) — the KubeRay-style truth is the cluster's
        try:
            pods = self.api.list_pods(
                f"{LABEL_CLUSTER}={self.cluster_name}")
            alive = {p["metadata"]["name"] for p in pods
                     if p.get("status", {}).get("phase")
                     in (None, "Pending", "Running")}
            # restartPolicy=Never pods that ran to Succeeded/Failed stay
            # in the namespace forever unless someone deletes them; every
            # listed pod carries our cluster label, so they're ours to
            # clean up (best-effort — a failed DELETE shows up in the
            # next list and retries then)
            terminal = [p["metadata"]["name"] for p in pods
                        if p.get("status", {}).get("phase")
                        in ("Succeeded", "Failed")]
        except Exception:
            return list(self._nodes)
        for name in terminal:
            try:
                self.api.delete_pod(name)
            except Exception:
                pass
        with self._lock:
            for name in list(self._nodes):
                if name not in alive:
                    self._nodes.pop(name, None)
                    self._types.pop(name, None)
            return list(self._nodes)

    def node_type_of(self, provider_id: str) -> str:
        return self._types[provider_id]

    def wait_running(self, provider_id: str, timeout: float = 300.0) -> dict:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            pod = self.api.get_pod(provider_id)
            if pod and pod.get("status", {}).get("phase") == "Running":
                return pod
            time.sleep(0.05)
        raise TimeoutError(f"pod {provider_id} not Running in {timeout}s")

    def shutdown(self) -> None:
        for pid in list(self._nodes):
            self.terminate_node(pid)
