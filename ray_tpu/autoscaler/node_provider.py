"""NodeProvider ABC + the local (subprocess) provider.

Parity: `python/ray/autoscaler/node_provider.py` ABC and the
fake-multi-node provider (`autoscaler/_private/fake_multi_node/
node_provider.py`) the reference uses to test autoscaling without a cloud:
here each "node" is a `node_main` daemon subprocess joining the head.
Cloud providers implement the same three methods against their fleet API.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Dict, List, Optional


class NodeProvider:
    """A node type is a dict: {"resources": {...}, "labels": {...},
    "max_nodes": int}."""

    def __init__(self, node_types: Dict[str, dict]):
        self.node_types = node_types

    def create_node(self, node_type: str) -> str:
        """Launch one node of `node_type`; returns a provider node id."""
        raise NotImplementedError

    def terminate_node(self, provider_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_type_of(self, provider_id: str) -> str:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    def __init__(self, node_types: Dict[str, dict], head_address: str):
        super().__init__(node_types)
        self.head_address = head_address
        self._procs: Dict[str, subprocess.Popen] = {}
        self._types: Dict[str, str] = {}
        self._counter = 0

    def create_node(self, node_type: str) -> str:
        spec = self.node_types[node_type]
        self._counter += 1
        provider_id = f"local-{node_type}-{self._counter}"
        import json

        from ray_tpu.core.resources import strip_device_env

        res = dict(spec.get("resources", {"CPU": 1}))
        cmd = [sys.executable, "-m", "ray_tpu.core.node_main",
               "--address", self.head_address,
               "--resources", json.dumps(res)]
        labels = {**spec.get("labels", {}),
                  "ray_tpu.io/provider-node-id": provider_id}
        cmd += ["--labels", json.dumps(labels)]
        self._procs[provider_id] = subprocess.Popen(
            cmd, env=strip_device_env(dict(os.environ)),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        self._types[provider_id] = node_type
        return provider_id

    def terminate_node(self, provider_id: str) -> None:
        proc = self._procs.pop(provider_id, None)
        self._types.pop(provider_id, None)
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    def non_terminated_nodes(self) -> List[str]:
        return [pid for pid, p in self._procs.items() if p.poll() is None]

    def node_type_of(self, provider_id: str) -> str:
        return self._types[provider_id]

    def shutdown(self) -> None:
        for pid in list(self._procs):
            self.terminate_node(pid)
