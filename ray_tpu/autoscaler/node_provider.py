"""NodeProvider ABC + the local (subprocess) provider.

Parity: `python/ray/autoscaler/node_provider.py` ABC and the
fake-multi-node provider (`autoscaler/_private/fake_multi_node/
node_provider.py`) the reference uses to test autoscaling without a cloud:
here each "node" is a `node_main` daemon subprocess joining the head.
Cloud providers implement the same three methods against their fleet API.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Dict, List, Optional


class NodeProvider:
    """A node type is a dict: {"resources": {...}, "labels": {...},
    "max_nodes": int}."""

    def __init__(self, node_types: Dict[str, dict]):
        self.node_types = node_types

    def create_node(self, node_type: str) -> str:
        """Launch one node of `node_type`; returns a provider node id."""
        raise NotImplementedError

    def terminate_node(self, provider_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_type_of(self, provider_id: str) -> str:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    def __init__(self, node_types: Dict[str, dict], head_address: str):
        super().__init__(node_types)
        self.head_address = head_address
        self._procs: Dict[str, subprocess.Popen] = {}
        self._types: Dict[str, str] = {}
        self._counter = 0

    def create_node(self, node_type: str) -> str:
        spec = self.node_types[node_type]
        self._counter += 1
        provider_id = f"local-{node_type}-{self._counter}"
        import json

        from ray_tpu.core.resources import strip_device_env

        res = dict(spec.get("resources", {"CPU": 1}))
        cmd = [sys.executable, "-m", "ray_tpu.core.node_main",
               "--address", self.head_address,
               "--resources", json.dumps(res)]
        labels = {**spec.get("labels", {}),
                  "ray_tpu.io/provider-node-id": provider_id}
        cmd += ["--labels", json.dumps(labels)]
        self._procs[provider_id] = subprocess.Popen(
            cmd, env=strip_device_env(dict(os.environ)),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        self._types[provider_id] = node_type
        return provider_id

    def terminate_node(self, provider_id: str) -> None:
        proc = self._procs.pop(provider_id, None)
        self._types.pop(provider_id, None)
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    def non_terminated_nodes(self) -> List[str]:
        return [pid for pid, p in self._procs.items() if p.poll() is None]

    def node_type_of(self, provider_id: str) -> str:
        return self._types[provider_id]

    def shutdown(self) -> None:
        for pid in list(self._procs):
            self.terminate_node(pid)


class SSHNodeProvider(NodeProvider):
    """Scales over a fixed fleet of SSH-reachable machines (reference
    `autoscaler/_private/local/node_provider.py` — the "local" provider's
    on-prem host-pool model, driven through the CommandRunner seam).

    Node types carry a `hosts` list; `create_node` claims the next free
    host and starts a node daemon joining the head, `terminate_node`
    stops it and returns the host to the pool. The same seam the cluster
    launcher uses, so `ray-tpu up` + autoscaler share one transport.
    """

    def __init__(self, node_types: Dict[str, dict], head_address: str,
                 auth: Optional[dict] = None, python: Optional[str] = None):
        super().__init__(node_types)
        from ray_tpu.autoscaler.command_runner import make_runner

        self.head_address = head_address
        self.auth = auth or {}
        self.python = python or sys.executable
        self._make_runner = make_runner
        self._nodes: Dict[str, dict] = {}   # provider_id -> host cfg
        self._types: Dict[str, str] = {}
        self._counter = 0

    def _free_host(self, node_type: str) -> Optional[dict]:
        used = {n["host"] for n in self._nodes.values()}
        for host in self.node_types[node_type].get("hosts", []):
            cfg = host if isinstance(host, dict) else {"host": host}
            if cfg["host"] not in used:
                return cfg
        return None

    def create_node(self, node_type: str) -> str:
        import json as _json
        import shlex
        import threading

        cfg = self._free_host(node_type)
        if cfg is None:
            raise RuntimeError(f"no free host for node type {node_type!r}")
        spec = self.node_types[node_type]
        self._counter += 1
        provider_id = f"ssh-{node_type}-{self._counter}"
        runner = self._make_runner(cfg, self.auth)
        flags = ""
        res = spec.get("resources")
        if res:
            flags += f" --resources {shlex.quote(_json.dumps(res))}"
        # provider-node-id label: how the autoscaler correlates this
        # provider node with its head registration (idle detection and
        # scale-down are impossible without it); spec labels ride along
        labels = {**spec.get("labels", {}),
                  "ray_tpu.io/provider-node-id": provider_id}
        flags += f" --labels {shlex.quote(_json.dumps(labels))}"
        # claim the host NOW, start in the background: an SSH round trip
        # (up to ~2 min) inside the autoscaler tick would serialize
        # scale-up and freeze idle-node termination meanwhile
        entry = {**cfg, "pid": None, "failed": False}
        self._nodes[provider_id] = entry
        self._types[provider_id] = node_type

        def _start():
            from ray_tpu.autoscaler.launcher import parse_daemon_pid

            try:
                rc, out = runner.run(
                    f"{self.python} -m ray_tpu.scripts.cli start "
                    f"--address {self.head_address}{flags}", timeout=120)
            except Exception:
                rc, out = 1, "runner raised"
            if rc != 0:
                entry["failed"] = True  # host back to the pool next scan
                self._nodes.pop(provider_id, None)
                self._types.pop(provider_id, None)
                return
            entry["pid"] = parse_daemon_pid(out)
            # terminate_node may have run while the SSH start was in
            # flight (entry popped, pid still None then): it could not
            # kill a pid it didn't know. Reap the daemon we just started
            # so the host really is free when back in the pool.
            if entry.get("terminating") and entry["pid"]:
                try:
                    runner.run(f"kill {entry['pid']} 2>/dev/null || true",
                               timeout=30)
                except Exception:
                    pass

        threading.Thread(target=_start, daemon=True,
                         name=f"ssh-start-{provider_id}").start()
        return provider_id

    def terminate_node(self, provider_id: str) -> None:
        cfg = self._nodes.pop(provider_id, None)
        self._types.pop(provider_id, None)
        if cfg is None:
            return
        # _start may still be mid-SSH with pid unknown; flag the entry so
        # it kills the daemon it is about to create (see _start).
        cfg["terminating"] = True
        runner = self._make_runner(cfg, self.auth)
        try:
            if cfg.get("pid"):
                # the recorded daemon only — never every ray-tpu process
                # on a (possibly shared) host
                runner.run(f"kill {cfg['pid']} 2>/dev/null || true",
                           timeout=30)
        except Exception:
            pass

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def node_type_of(self, provider_id: str) -> str:
        return self._types[provider_id]

    def shutdown(self) -> None:
        for pid in list(self._nodes):
            self.terminate_node(pid)
