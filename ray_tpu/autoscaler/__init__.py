"""Autoscaler: demand-driven node scale-up/down over a NodeProvider.

Parity (core subset) with `python/ray/autoscaler/_private/autoscaler.py`
(StandardAutoscaler + resource_demand_scheduler): read unmet resource
demand from the head, bin-pack it onto provider node types, launch/terminate
nodes; idle non-head nodes are reclaimed after `idle_timeout_s`.
"""

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.node_provider import LocalNodeProvider, NodeProvider

__all__ = ["StandardAutoscaler", "NodeProvider", "LocalNodeProvider"]
