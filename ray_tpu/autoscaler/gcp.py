"""GCP node provider: create/terminate GCE instances and TPU-VM slices.

Behavioral parity with the reference's GCP integration
(`python/ray/autoscaler/_private/gcp/node_provider.py:63 GCPNodeProvider`,
`gcp/node.py` GCPCompute/GCPTPU, `gcp/tpu_command_runner.py:148`), rebuilt
for this runtime's provider seam:

- Two GCP resource families behind one provider: **Compute Engine
  instances** (CPU/host nodes) and **TPU VMs** (`tpu.googleapis.com/v2`
  nodes, including multi-host pod slices). Which family a node type uses
  is declared in its `gcp:` block (`type: compute|tpu`).
- A **TPU pod slice is ONE provider node**: `create_node` creates the
  slice, then fans the node-daemon start over every host via
  `TPUCommandRunner` (reference wraps SSHCommandRunner N times,
  `tpu_command_runner.py:148` — same design here). Worker 0 advertises the
  `TPU-{pod}-head` resource; every host carries the slice labels
  (`ray.io/tpu-slice-name|worker-id|pod-type|topology`) so placement
  groups can gang-schedule onto the slice.
- All HTTP goes through one injectable `request_fn(method, url, body)`
  seam so tests run against a fake in-process GCP (no googleapiclient
  dependency; auth = metadata-server token by default).

The provider implements the same 4-method NodeProvider interface the
autoscaler's bin-packing loop drives, so `_spawn_for_demand`-style
scale-up and idle scale-down work unchanged against real TPU fleets.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.command_runner import (CommandRunner, make_runner)
from ray_tpu.autoscaler.node_provider import NodeProvider

COMPUTE_URL = "https://compute.googleapis.com/compute/v1"
TPU_URL = "https://tpu.googleapis.com/v2"
TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
             "instance/service-accounts/default/token")

# instance labels (GCP labels must be lowercase [a-z0-9_-])
LABEL_CLUSTER = "ray-tpu-cluster"
LABEL_NODE_TYPE = "ray-tpu-node-type"
LABEL_PROVIDER_ID = "ray-tpu-provider-id"


def _metadata_token() -> str:
    req = urllib.request.Request(TOKEN_URL,
                                 headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())["access_token"]


def default_request_fn(method: str, url: str,
                       body: Optional[dict]) -> Tuple[int, dict]:
    """Real-GCP transport: bearer token from the metadata server."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Authorization": f"Bearer {_metadata_token()}",
                 "Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        try:
            return e.code, json.loads(payload)
        except (ValueError, TypeError):
            return e.code, {"error": payload.decode(errors="replace")}


def api_from_config(provider_cfg: dict) -> "GCPApi":
    """cluster.yaml `provider:` block → GCPApi. The launcher and `down`
    both resolve their API through this module-level seam so tests swap
    ONE factory for a fake in-process GCP."""
    return GCPApi(provider_cfg["project"],
                  provider_cfg.get("zone")
                  or provider_cfg.get("availability_zone"))


class GCPApiError(RuntimeError):
    def __init__(self, status: int, body: dict):
        super().__init__(f"GCP API error {status}: {body}")
        self.status = status
        self.body = body


class GCPApi:
    """Minimal typed wrapper over the two REST surfaces the provider
    needs. `request_fn` is the test seam (reference achieves the same by
    mocking googleapiclient discovery objects)."""

    def __init__(self, project: str, zone: str,
                 request_fn: Callable[..., Tuple[int, dict]] = None,
                 op_poll_s: float = 2.0, op_max_polls: int = 150):
        self.project, self.zone = project, zone
        self.request_fn = request_fn or default_request_fn
        self.op_poll_s, self.op_max_polls = op_poll_s, op_max_polls

    def _call(self, method: str, url: str, body: dict = None,
              ok_missing: bool = False) -> dict:
        status, payload = self.request_fn(method, url, body)
        if status == 404 and ok_missing:
            return {}
        if status >= 300:
            raise GCPApiError(status, payload)
        return payload

    # ------------------------------------------------------- Compute Engine
    @property
    def _zone_url(self) -> str:
        return f"{COMPUTE_URL}/projects/{self.project}/zones/{self.zone}"

    def insert_instance(self, body: dict) -> dict:
        op = self._call("POST", f"{self._zone_url}/instances", body)
        return self.wait_zone_operation(op)

    def delete_instance(self, name: str) -> dict:
        op = self._call("DELETE", f"{self._zone_url}/instances/{name}",
                        ok_missing=True)
        return self.wait_zone_operation(op) if op else {}

    def get_instance(self, name: str) -> Optional[dict]:
        got = self._call("GET", f"{self._zone_url}/instances/{name}",
                         ok_missing=True)
        return got or None

    def list_instances(self) -> List[dict]:
        return self._call("GET", f"{self._zone_url}/instances").get(
            "items", [])

    def set_instance_labels(self, name: str, labels: dict) -> dict:
        inst = self.get_instance(name) or {}
        body = {"labels": {**inst.get("labels", {}), **labels},
                "labelFingerprint": inst.get("labelFingerprint", "")}
        op = self._call("POST",
                        f"{self._zone_url}/instances/{name}/setLabels", body)
        return self.wait_zone_operation(op)

    def wait_zone_operation(self, op: dict) -> dict:
        for _ in range(self.op_max_polls):
            if op.get("status") == "DONE":
                if "error" in op:
                    raise GCPApiError(500, op["error"])
                return op
            time.sleep(self.op_poll_s)
            op = self._call(
                "GET", f"{self._zone_url}/operations/{op['name']}")
        raise TimeoutError(f"GCE operation {op.get('name')} did not finish")

    # ------------------------------------------------------------- TPU VMs
    @property
    def _tpu_parent(self) -> str:
        return (f"{TPU_URL}/projects/{self.project}/"
                f"locations/{self.zone}")

    def create_tpu_node(self, node_id: str, body: dict) -> dict:
        op = self._call("POST",
                        f"{self._tpu_parent}/nodes?nodeId={node_id}", body)
        return self.wait_tpu_operation(op)

    def delete_tpu_node(self, name: str) -> dict:
        op = self._call("DELETE", f"{self._tpu_parent}/nodes/{name}",
                        ok_missing=True)
        return self.wait_tpu_operation(op) if op else {}

    def get_tpu_node(self, name: str) -> Optional[dict]:
        got = self._call("GET", f"{self._tpu_parent}/nodes/{name}",
                         ok_missing=True)
        return got or None

    def list_tpu_nodes(self) -> List[dict]:
        return self._call("GET", f"{self._tpu_parent}/nodes").get(
            "nodes", [])

    def patch_tpu_labels(self, name: str, labels: dict) -> dict:
        node = self.get_tpu_node(name) or {}
        body = {"labels": {**node.get("labels", {}), **labels}}
        op = self._call(
            "PATCH", f"{self._tpu_parent}/nodes/{name}?updateMask=labels",
            body)
        return self.wait_tpu_operation(op)

    def wait_tpu_operation(self, op: dict) -> dict:
        for _ in range(self.op_max_polls):
            if op.get("done"):
                if "error" in op:
                    raise GCPApiError(500, op["error"])
                return op
            time.sleep(self.op_poll_s)
            op = self._call("GET", f"{TPU_URL}/{op['name']}")
        raise TimeoutError(f"TPU operation {op.get('name')} did not finish")


class TPUCommandRunner(CommandRunner):
    """Fan one CommandRunner call to every host of a TPU pod slice
    (reference `gcp/tpu_command_runner.py` — a pod is one Ray node, so
    CommandRunnerInterface operations run N times, batched in threads).
    `run` returns the worst rc and the per-host outputs concatenated."""

    def __init__(self, runners: List[CommandRunner]):
        self.runners = runners

    def _fan(self, fn_name: str, *args, **kwargs):
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=max(1, len(self.runners))) as ex:
            futs = [ex.submit(getattr(r, fn_name), *args, **kwargs)
                    for r in self.runners]
            return [f.result() for f in futs]

    def run(self, cmd, timeout=None, env=None):
        results = self._fan("run", cmd, timeout=timeout, env=env)
        rc = max((r[0] for r in results), default=0)
        out = "\n".join(f"[worker {i}] {r[1]}"
                        for i, r in enumerate(results))
        return rc, out

    def rsync_up(self, source, target):
        self._fan("rsync_up", source, target)

    def rsync_down(self, source, target):
        # pod-level download only makes sense from worker 0
        self.runners[0].rsync_down(source, target)

    def remote_shell_command(self):
        return self.runners[0].remote_shell_command()


def _tpu_host_ips(node: dict, internal: bool = False) -> List[str]:
    """Per-host reachable IPs of a (possibly multi-host) TPU node, in
    worker-id order (`networkEndpoints` order is the worker order)."""
    ips = []
    for ep in node.get("networkEndpoints", []):
        if internal:
            ips.append(ep.get("ipAddress"))
        else:
            acc = ep.get("accessConfig") or {}
            ips.append(acc.get("externalIp") or ep.get("ipAddress"))
    return [ip for ip in ips if ip]


def _gce_instance_ip(inst: dict, internal: bool = False) -> Optional[str]:
    for nic in inst.get("networkInterfaces", []):
        if not internal:
            for ac in nic.get("accessConfigs", []):
                if ac.get("natIP"):
                    return ac["natIP"]
        if nic.get("networkIP"):
            return nic["networkIP"]
    return None


class GCPNodeProvider(NodeProvider):
    """Node types (cluster.yaml `worker_node_types`) gain a `gcp:` block:

    ```yaml
    tpu_slice:
      max_nodes: 2
      resources: {TPU: 8}            # per-HOST advertised capacity
      gcp:
        type: tpu
        accelerator_type: v4-16      # >8 chips -> multi-host slice
        runtime_version: tpu-ubuntu2204-base
    cpu_worker:
      max_nodes: 4
      resources: {CPU: 16}
      gcp:
        type: compute
        machine_type: n2-standard-16
        source_image: projects/debian-cloud/global/images/family/debian-12
    ```

    `create_node` returns immediately after issuing the cloud create; a
    starter thread waits for READY/RUNNING, then SSH-starts the node
    daemon(s) — one per TPU host — joining `head_address`, labelled so the
    autoscaler can correlate head registrations with provider nodes and so
    TPU gang scheduling sees the slice.
    """

    def __init__(self, node_types: Dict[str, dict], head_address: str,
                 auth: Optional[dict] = None, python: Optional[str] = None,
                 *, project: str, zone: str, cluster_name: str = "default",
                 api: Optional[GCPApi] = None, use_internal_ips: bool = False):
        super().__init__(node_types)
        import sys

        self.head_address = head_address
        self.auth = auth or {}
        self.python = python or sys.executable
        self.cluster_name = cluster_name
        self.api = api or GCPApi(project, zone)
        self.use_internal_ips = use_internal_ips
        self._make_runner = make_runner
        self._nodes: Dict[str, dict] = {}    # provider_id -> entry
        self._types: Dict[str, str] = {}
        self._counter = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------ helpers
    def _is_tpu(self, node_type: str) -> bool:
        return (self.node_types[node_type].get("gcp", {})
                .get("type", "compute") == "tpu"
                or "accelerator_type" in
                self.node_types[node_type].get("gcp", {}))

    def _instance_name(self, node_type: str) -> str:
        self._counter += 1
        kind = "tpu" if self._is_tpu(node_type) else "compute"
        # reference names are '[cluster]-[uuid]-[type]'; counter is enough
        # for one provider process and keeps test output deterministic
        return (f"{self.cluster_name}-{node_type}-{self._counter}-{kind}"
                .replace("_", "-").lower())

    def _labels(self, node_type: str, provider_id: str) -> dict:
        return {LABEL_CLUSTER: self.cluster_name,
                LABEL_NODE_TYPE: node_type.replace("_", "-").lower(),
                LABEL_PROVIDER_ID: provider_id.replace("_", "-").lower()}

    # --------------------------------------------------------- create path
    def create_node(self, node_type: str) -> str:
        with self._lock:
            name = self._instance_name(node_type)
        provider_id = name
        entry = {"name": name, "node_type": node_type, "hosts": [],
                 "is_tpu": self._is_tpu(node_type),
                 "ready": False, "failed": False, "terminating": False}
        with self._lock:
            self._nodes[provider_id] = entry
            self._types[provider_id] = node_type

        def _create():
            try:
                if self._is_tpu(node_type):
                    self._create_tpu(name, node_type, provider_id, entry)
                else:
                    self._create_compute(name, node_type, provider_id, entry)
                entry["ready"] = True
            except Exception as e:  # creation failed: release the slot
                entry["failed"] = True
                entry["error"] = repr(e)
                with self._lock:
                    self._nodes.pop(provider_id, None)
                    self._types.pop(provider_id, None)
                # best-effort cloud cleanup of a half-created instance
                try:
                    if self._is_tpu(node_type):
                        self.api.delete_tpu_node(name)
                    else:
                        self.api.delete_instance(name)
                except Exception:
                    pass
                return
            if entry["terminating"]:
                # terminate_node raced the create; reap what we just made
                self._cloud_delete(entry)

        threading.Thread(target=_create, daemon=True,
                         name=f"gcp-create-{name}").start()
        return provider_id

    def _create_compute(self, name: str, node_type: str,
                        provider_id: str, entry: dict) -> None:
        gcp = self.node_types[node_type].get("gcp", {})
        self._create_instance_body_and_insert(name, node_type, gcp)
        inst = self.api.get_instance(name)
        if not inst or inst.get("status") != "RUNNING":
            raise RuntimeError(f"instance {name} not RUNNING after create")
        ip = _gce_instance_ip(inst, self.use_internal_ips)
        if not ip:
            raise RuntimeError(f"instance {name} has no reachable IP")
        entry["hosts"] = [{"host": ip}]
        self._start_daemons(entry, node_type, provider_id, tpu_node=None)

    def _create_tpu(self, name: str, node_type: str,
                    provider_id: str, entry: dict) -> None:
        gcp = self.node_types[node_type].get("gcp", {})
        body = {
            "acceleratorType": gcp.get("accelerator_type", "v4-8"),
            "runtimeVersion": gcp.get("runtime_version",
                                      "tpu-ubuntu2204-base"),
            "labels": self._labels(node_type, provider_id),
            "networkConfig": {"enableExternalIps":
                              not self.use_internal_ips},
            **gcp.get("extra_config", {}),
        }
        self.api.create_tpu_node(name, body)
        node = self.api.get_tpu_node(f"{name}")
        if not node or node.get("state") not in ("READY", "RUNNING"):
            raise RuntimeError(f"TPU node {name} not READY after create")
        ips = _tpu_host_ips(node, self.use_internal_ips)
        if not ips:
            raise RuntimeError(f"TPU node {name} has no host endpoints")
        entry["hosts"] = [{"host": ip} for ip in ips]
        self._start_daemons(entry, node_type, provider_id, tpu_node=node)

    def _start_daemons(self, entry: dict, node_type: str,
                       provider_id: str, tpu_node: Optional[dict]) -> None:
        """SSH every host of the (possibly multi-host) node and start a
        node daemon joining the head. TPU hosts get slice labels; worker 0
        gets the `TPU-{pod}-head` gang resource (reference
        `tpu_command_runner.py` head-resource interception +
        `accelerators/tpu.py:482-545` extra resources)."""
        import shlex

        spec = self.node_types[node_type]
        pod_type = (tpu_node or {}).get("acceleratorType") or \
            spec.get("gcp", {}).get("accelerator_type")
        topology = ((tpu_node or {}).get("acceleratorConfig") or {}) \
            .get("topology")
        errs = []
        for worker_id, host_cfg in enumerate(entry["hosts"]):
            runner = self._make_runner(host_cfg, self.auth)
            labels = {**spec.get("labels", {}),
                      "ray_tpu.io/provider-node-id": provider_id}
            resources = dict(spec.get("resources", {}))
            if tpu_node is not None:
                labels.update({
                    "ray.io/tpu-slice-name": entry["name"],
                    "ray.io/tpu-worker-id": str(worker_id),
                })
                if pod_type:
                    labels["ray.io/tpu-pod-type"] = pod_type
                if topology:
                    labels["ray.io/tpu-topology"] = topology
                if worker_id == 0 and pod_type:
                    resources[f"TPU-{pod_type}-head"] = 1
            flags = f" --labels {shlex.quote(json.dumps(labels))}"
            if resources:
                flags += f" --resources {shlex.quote(json.dumps(resources))}"
            rc, out = runner.run(
                f"{self.python} -m ray_tpu.scripts.cli start "
                f"--address {self.head_address}{flags}", timeout=300)
            if rc != 0:
                errs.append(f"worker {worker_id}: {out}")
            else:
                from ray_tpu.autoscaler.launcher import parse_daemon_pid

                host_cfg["pid"] = parse_daemon_pid(out)
        if errs:
            raise RuntimeError(
                f"daemon start failed on {len(errs)} host(s) of "
                f"{entry['name']}: " + "; ".join(errs))

    # ------------------------------------------------------ terminate path
    def _cloud_delete(self, entry: dict) -> None:
        try:
            if entry["is_tpu"]:
                self.api.delete_tpu_node(entry["name"])
            else:
                self.api.delete_instance(entry["name"])
        except Exception:
            pass

    def terminate_node(self, provider_id: str) -> None:
        with self._lock:
            entry = self._nodes.pop(provider_id, None)
            self._types.pop(provider_id, None)
        if entry is None:
            return
        entry["terminating"] = True
        if entry["ready"] or entry["failed"]:
            self._cloud_delete(entry)
        # else: the creator thread observes `terminating` and reaps

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def node_type_of(self, provider_id: str) -> str:
        return self._types[provider_id]

    def shutdown(self) -> None:
        for pid in list(self._nodes):
            self.terminate_node(pid)

    # ----------------------------------------------------- launcher hooks
    def create_raw_instance(self, node_type: str) -> Tuple[str, List[dict]]:
        """Synchronously create the cloud instance(s) for `node_type`
        WITHOUT starting node daemons — the launcher uses this for the
        head VM (there is no head to join yet). Returns
        (provider_id, host cfg list in worker order)."""
        with self._lock:
            name = self._instance_name(node_type)
        entry = {"name": name, "node_type": node_type, "hosts": [],
                 "is_tpu": self._is_tpu(node_type),
                 "ready": False, "failed": False, "terminating": False}
        with self._lock:
            self._nodes[name] = entry
            self._types[name] = node_type
        gcp = self.node_types[node_type].get("gcp", {})
        if entry["is_tpu"]:
            body = {"acceleratorType": gcp.get("accelerator_type", "v4-8"),
                    "runtimeVersion": gcp.get("runtime_version",
                                              "tpu-ubuntu2204-base"),
                    "labels": self._labels(node_type, name),
                    "networkConfig": {"enableExternalIps":
                                      not self.use_internal_ips},
                    **gcp.get("extra_config", {})}
            self.api.create_tpu_node(name, body)
            node = self.api.get_tpu_node(name)
            ips = _tpu_host_ips(node or {}, self.use_internal_ips)
            entry["hosts"] = [{"host": ip} for ip in ips]
        else:
            self._create_instance_body_and_insert(name, node_type, gcp)
            inst = self.api.get_instance(name)
            ip = _gce_instance_ip(inst or {}, self.use_internal_ips)
            entry["hosts"] = [{"host": ip}] if ip else []
        if not entry["hosts"]:
            raise RuntimeError(f"instance {name} has no reachable hosts")
        entry["ready"] = True
        return name, entry["hosts"]

    def _create_instance_body_and_insert(self, name: str, node_type: str,
                                         gcp: dict) -> None:
        machine = gcp.get("machine_type", "n2-standard-8")
        body = {
            "name": name,
            "machineType": f"zones/{self.api.zone}/machineTypes/{machine}",
            "labels": self._labels(node_type, name),
            "disks": [{"boot": True, "initializeParams": {
                "sourceImage": gcp.get(
                    "source_image",
                    "projects/debian-cloud/global/images/family/debian-12")}}],
            "networkInterfaces": [{"network": "global/networks/default",
                                   "accessConfigs":
                                       [{"type": "ONE_TO_ONE_NAT"}]}],
            **gcp.get("extra_config", {}),
        }
        self.api.insert_instance(body)

    def command_runner_for(self, provider_id: str) -> CommandRunner:
        """A runner addressing the node — a TPU pod slice gets the fan-out
        runner over all hosts (reference TPUCommandRunner)."""
        entry = self._nodes[provider_id]
        runners = [self._make_runner(h, self.auth) for h in entry["hosts"]]
        if len(runners) == 1:
            return runners[0]
        return TPUCommandRunner(runners)

    def wait_ready(self, provider_id: str, timeout: float = 600.0) -> dict:
        """Block until the background create finished (launcher head
        bring-up needs the IP before it can proceed)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            entry = self._nodes.get(provider_id)
            if entry is None:
                raise RuntimeError(
                    f"node {provider_id} failed to create")
            if entry["ready"]:
                return entry
            time.sleep(0.05)
        raise TimeoutError(f"node {provider_id} not ready in {timeout}s")
