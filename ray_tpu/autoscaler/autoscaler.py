"""StandardAutoscaler: the reactive scaling loop.

Parity: `autoscaler/_private/autoscaler.py` StandardAutoscaler +
`resource_demand_scheduler.py` — each tick: read unmet demand from the head,
bin-pack demand onto node types (first-fit over per-type capacity), launch
up to `max_launch_batch` nodes, and terminate nodes idle longer than
`idle_timeout_s`. Runs as a driver-side thread (the reference runs the same
loop in the head-node `monitor.py` process).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


def bin_pack(demand: List[Dict[str, float]],
             node_types: Dict[str, dict],
             headroom: Optional[Dict[str, int]] = None,
             pending_capacity: Optional[List[Dict[str, float]]] = None
             ) -> Dict[str, int]:
    """First-fit-decreasing: how many nodes of each type to add to satisfy
    `demand` (list of resource asks), respecting per-type max_nodes minus
    already-running counts in `headroom`. `pending_capacity` (e.g. nodes
    already launched but still booting) absorbs demand before anything new
    is launched."""
    headroom = dict(headroom or {})
    to_launch: Dict[str, int] = {}
    # remaining capacity per new/booting node
    open_nodes: List[Dict[str, float]] = [dict(c) for c in pending_capacity or []]
    for ask in sorted(demand, key=lambda d: -sum(d.values())):
        placed = False
        for cap in open_nodes:
            if all(cap.get(r, 0) >= v for r, v in ask.items()):
                for r, v in ask.items():
                    cap[r] -= v
                placed = True
                break
        if placed:
            continue
        for t, spec in node_types.items():
            res = spec.get("resources", {})
            used = headroom.get(t, 0) + to_launch.get(t, 0)
            if used >= spec.get("max_nodes", 1):
                continue
            if all(res.get(r, 0) >= v for r, v in ask.items()):
                to_launch[t] = to_launch.get(t, 0) + 1
                cap = dict(res)
                for r, v in ask.items():
                    cap[r] -= v
                open_nodes.append(cap)
                placed = True
                break
        # unplaceable asks are simply skipped (reference logs them as
        # infeasible demand)
    return to_launch


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider, *,
                 idle_timeout_s: float = 60.0,
                 poll_interval_s: float = 1.0,
                 max_launch_batch: int = 8):
        self.provider = provider
        self.idle_timeout_s = idle_timeout_s
        self.poll_interval_s = poll_interval_s
        self.max_launch_batch = max_launch_batch
        self._idle_since: Dict[str, float] = {}
        # provider_id -> (node_type, launch_ts): launched, not yet registered
        self._booting: Dict[str, tuple] = {}
        self.boot_timeout_s = 120.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_launches = 0
        self.num_terminations = 0

    # ------------------------------------------------------------- one tick
    def update(self) -> None:
        from ray_tpu.core.api import _global_client

        client = _global_client()
        demand = client.head_request("cluster_demand")
        nodes = client.head_request("list_state", kind="nodes")
        # one provider node may register SEVERAL head nodes (a TPU pod
        # slice = one provider node, one daemon per host) — group them
        by_provider_id: Dict[str, list] = {}
        for n in nodes:
            if not n["is_head"]:
                by_provider_id.setdefault(
                    n["labels"].get("ray_tpu.io/provider-node-id"),
                    []).append(n)

        # a launched node is "booting" until it registers with the head
        # (or times out); its capacity absorbs demand so the same unmet ask
        # doesn't trigger a fresh launch every poll tick
        now0 = time.time()
        alive = self.provider.non_terminated_nodes()
        for pid, (_t, ts) in list(self._booting.items()):
            if (pid in by_provider_id or pid not in alive
                    or now0 - ts > self.boot_timeout_s):
                del self._booting[pid]

        # scale up
        if demand:
            running_counts: Dict[str, int] = {}
            for pid in alive:
                t = self.provider.node_type_of(pid)
                running_counts[t] = running_counts.get(t, 0) + 1
            pending_cap = [dict(self.provider.node_types[t].get("resources", {}))
                           for t, _ts in self._booting.values()]
            plan = bin_pack(demand, self.provider.node_types, running_counts,
                            pending_capacity=pending_cap)
            budget = self.max_launch_batch
            for node_type, count in plan.items():
                for _ in range(min(count, budget)):
                    pid = self.provider.create_node(node_type)
                    self._booting[pid] = (node_type, time.time())
                    self.num_launches += 1
                budget -= min(count, budget)

        # scale down: idle (all resources free, no workers busy) too long
        now = time.time()
        for pid in self.provider.non_terminated_nodes():
            ns = by_provider_id.get(pid)
            if not ns:
                continue  # still booting/registering
            busy = any(n["available"].get(r, 0) < v
                       for n in ns for r, v in n["resources"].items())
            if busy or demand:
                self._idle_since.pop(pid, None)
                continue
            since = self._idle_since.setdefault(pid, now)
            if now - since > self.idle_timeout_s:
                self.provider.terminate_node(pid)
                self._idle_since.pop(pid, None)
                self.num_terminations += 1

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.poll_interval_s):
                try:
                    self.update()
                except Exception:
                    pass  # transient head hiccups must not kill the loop

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
