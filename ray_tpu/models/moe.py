"""Mixture-of-Experts transformer (Mixtral-style) with expert parallelism.

Capability parity: the reference exposes expert parallelism only as vLLM
engine flags plus placement groups (SURVEY.md §2.13, `python/ray/llm/_internal/
serve/deployments/llm/vllm/vllm_models.py`) — it ships no MoE math. Here the
framework owns a TPU-first sparse-MoE layer:

- experts are STACKED (`[n_experts, ...]` leading dim) and sharded over the
  `ep` mesh axis (logical axis "expert");
- routing uses the dense one-hot dispatch/combine formulation (einsums, not
  gather/scatter): top-k gating -> capacity-bounded position assignment ->
  `dispatch [N,E,C]` / `combine [N,E,C]` masks -> three einsums that XLA maps
  onto the MXU and turns into an all-to-all over `ep` when experts are
  sharded. Static shapes throughout (capacity factor bounds expert load), so
  the whole thing jits once;
- standard Switch-style load-balance auxiliary loss + router z-loss;
- attention/norm blocks are reused from `ray_tpu.models.llama`.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models import llama as _llama
from ray_tpu.parallel.mesh import constrain, logical_to_spec

Params = Any


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 8
    d_model: int = 4096
    d_ff: int = 14336                # per-expert SwiGLU hidden size
    n_experts: int = 8
    experts_per_token: int = 2       # top-k routing
    capacity_factor: float = 1.25    # C = ceil(k*T/E * factor), padded tokens drop
    aux_loss_weight: float = 0.01    # Switch load-balance loss
    z_loss_weight: float = 1e-3      # router logit z-loss
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    attn_impl: str = "auto"
    tie_embeddings: bool = False

    # llama-block compatibility
    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def q_per_kv(self) -> int:
        return self.n_head // self.n_kv_head

    @classmethod
    def preset(cls, name: str, **overrides) -> "MoEConfig":
        presets = {
            "mixtral-8x7b": dict(n_layer=32, n_head=32, n_kv_head=8,
                                 d_model=4096, d_ff=14336, n_experts=8,
                                 experts_per_token=2, vocab_size=32000),
            "moe-tiny": dict(n_layer=2, n_head=4, n_kv_head=2, d_model=128,
                             d_ff=256, n_experts=4, experts_per_token=2,
                             vocab_size=512, max_seq_len=128),
        }
        return cls(**{**presets[name], **overrides})


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def init_params(key: jax.Array, cfg: MoEConfig) -> Params:
    k_emb, k_head, k_blocks = jax.random.split(key, 3)
    pd = cfg.param_dtype
    D, Dh, E, F = cfg.d_model, cfg.head_dim, cfg.n_experts, cfg.d_ff
    kv_dim = cfg.n_kv_head * Dh
    std = 0.02
    resid_std = std / math.sqrt(2 * cfg.n_layer)

    def norm(k, shape, s=std):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(pd)

    def init_block(k):
        ks = jax.random.split(k, 8)
        return {
            "attn_norm": {"scale": jnp.ones((D,), pd)},
            "attn": {
                "wq": norm(ks[0], (D, D)),
                "wk": norm(ks[1], (D, kv_dim)),
                "wv": norm(ks[2], (D, kv_dim)),
                "wo": norm(ks[3], (D, D), resid_std),
            },
            "mlp_norm": {"scale": jnp.ones((D,), pd)},
            "moe": {
                "router": norm(ks[4], (D, E)),
                "wg": norm(ks[5], (E, D, F)),
                "wu": norm(ks[6], (E, D, F)),
                "wd": norm(ks[7], (E, F, D), resid_std),
            },
        }

    blocks = jax.vmap(init_block)(jax.random.split(k_blocks, cfg.n_layer))
    params = {
        "wte": norm(k_emb, (cfg.vocab_size, D)),
        "blocks": blocks,
        "final_norm": {"scale": jnp.ones((D,), pd)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(k_head, (D, cfg.vocab_size))
    return params


def param_logical_axes(cfg: MoEConfig) -> Params:
    block = {
        "attn_norm": {"scale": ("embed",)},
        "attn": {
            "wq": ("embed", "heads"),
            "wk": ("embed", "kv"),
            "wv": ("embed", "kv"),
            "wo": ("heads", "embed"),
        },
        "mlp_norm": {"scale": ("embed",)},
        "moe": {
            "router": ("embed", None),       # tiny; replicated
            "wg": ("expert", "embed", "mlp"),
            "wu": ("expert", "embed", "mlp"),
            "wd": ("expert", "mlp", "embed"),
        },
    }
    block = jax.tree.map(lambda axes: ("layers",) + axes, block,
                         is_leaf=lambda x: isinstance(x, tuple))
    axes = {
        "wte": ("vocab", "embed"),
        "blocks": block,
        "final_norm": {"scale": ("embed",)},
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def param_specs(cfg: MoEConfig, rules=None) -> Params:
    return jax.tree.map(
        lambda axes: logical_to_spec(*axes, rules=rules),
        param_logical_axes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


# ---------------------------------------------------------------------------
# Sparse MoE layer (dense dispatch/combine einsum formulation)
# ---------------------------------------------------------------------------

def expert_capacity(cfg: MoEConfig, n_tokens: int) -> int:
    c = math.ceil(cfg.experts_per_token * n_tokens * cfg.capacity_factor
                  / cfg.n_experts)
    return max(int(c), 4)


def moe_layer(x, p, cfg: MoEConfig):
    """Sparse SwiGLU MoE. x [B,T,D] -> (out [B,T,D], aux_metrics dict).

    Dense one-hot dispatch: every token gets top-k expert choices; a cumsum
    over the token axis assigns per-expert positions; tokens past capacity C
    are dropped (contribute zero — the residual stream carries them).
    """
    B, T, D = x.shape
    E, K = cfg.n_experts, cfg.experts_per_token
    N = B * T
    C = expert_capacity(cfg, T)  # capacity per expert per batch row

    xt = x.reshape(B, T, D)
    # Router in f32 for numerics.
    logits = (xt.astype(jnp.float32)
              @ p["router"].astype(jnp.float32))          # [B,T,E]
    probs = jax.nn.softmax(logits, axis=-1)

    gate_vals, gate_idx = lax.top_k(probs, K)             # [B,T,K]
    # Mixtral-style: renormalize the top-k gates to sum to 1.
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # One-hot expert assignment per routing slot: [B,T,K,E]
    assign = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)
    # Position of each (token, slot) within its expert queue: cumulative count
    # over (slot-major, then token) order so slot 0 choices win capacity ties.
    flat = assign.transpose(0, 2, 1, 3).reshape(B, K * T, E)   # slot-major
    pos_in_expert = jnp.cumsum(flat, axis=1) - flat            # [B,K*T,E]
    pos_in_expert = pos_in_expert.reshape(B, K, T, E).transpose(0, 2, 1, 3)
    within_cap = pos_in_expert < C                             # [B,T,K,E]
    keep = assign * within_cap                                 # [B,T,K,E]

    # Dispatch/combine tensors: [B,T,E,C]
    slot_pos = jnp.sum(pos_in_expert * assign, axis=-1).astype(jnp.int32)
    pos_oh = jax.nn.one_hot(slot_pos, C, dtype=jnp.float32)    # [B,T,K,C]
    dispatch = jnp.einsum("btke,btkc->btec", keep, pos_oh)
    combine = jnp.einsum("btke,btkc,btk->btec", keep, pos_oh, gate_vals)

    # Expert inputs: [E, B, C, D] — the einsum over the token axis is the
    # all-to-all when experts are ep-sharded and tokens dp-sharded.
    expert_in = jnp.einsum("btec,btd->ebcd", dispatch.astype(cfg.dtype), xt)
    expert_in = constrain(expert_in, "expert", "batch", None, "embed")

    def one_expert(inp, wg, wu, wd):
        g = inp @ wg.astype(cfg.dtype)
        u = inp @ wu.astype(cfg.dtype)
        return (jax.nn.silu(g) * u) @ wd.astype(cfg.dtype)

    expert_out = jax.vmap(one_expert)(expert_in, p["wg"], p["wu"], p["wd"])
    expert_out = constrain(expert_out, "expert", "batch", None, "embed")

    out = jnp.einsum("btec,ebcd->btd", combine.astype(cfg.dtype), expert_out)
    out = constrain(out, "batch", "seq", "embed")

    # Switch load-balance loss: E * sum_e f_e * p_e  (f = fraction of tokens
    # routed, p = mean router prob); plus z-loss on logits.
    frac = jnp.mean(jnp.sum(keep, axis=2), axis=(0, 1)) * (E / K)  # [E]
    mean_prob = jnp.mean(probs, axis=(0, 1)) * E                   # [E]
    aux_loss = jnp.mean(frac * mean_prob)
    z_loss = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    dropped = 1.0 - jnp.sum(keep) / (N * K)
    return out, {"aux_loss": aux_loss, "z_loss": z_loss,
                 "dropped_frac": dropped}


def _block(carry, bp, cfg: MoEConfig):
    x, aux_acc = carry
    x = x + _llama.attention(
        _llama.rms_norm(x, bp["attn_norm"], cfg.norm_eps), bp["attn"], cfg)
    x = constrain(x, "batch", "seq", "embed")
    moe_out, aux = moe_layer(
        _llama.rms_norm(x, bp["mlp_norm"], cfg.norm_eps), bp["moe"], cfg)
    x = x + moe_out
    x = constrain(x, "batch", "seq", "embed")
    aux_acc = {
        "aux_loss": aux_acc["aux_loss"] + aux["aux_loss"],
        "z_loss": aux_acc["z_loss"] + aux["z_loss"],
        "dropped_frac": aux_acc["dropped_frac"] + aux["dropped_frac"],
    }
    return (x, aux_acc)


# ---------------------------------------------------------------------------
# Forward / loss
# ---------------------------------------------------------------------------

def forward(params: Params, tokens: jax.Array, cfg: MoEConfig,
            return_aux: bool = False):
    x = params["wte"][tokens].astype(cfg.dtype)
    x = constrain(x, "batch", "seq", "embed")
    aux0 = {"aux_loss": jnp.zeros((), jnp.float32),
            "z_loss": jnp.zeros((), jnp.float32),
            "dropped_frac": jnp.zeros((), jnp.float32)}

    block_fn = partial(_block, cfg=cfg)
    if cfg.remat:
        block_fn = jax.checkpoint(block_fn)

    (x, aux), _ = lax.scan(lambda c, bp: (block_fn(c, bp), None),
                           (x, aux0), params["blocks"])
    x = _llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["wte"].T if cfg.tie_embeddings else params["lm_head"]
    logits = x @ head.astype(cfg.dtype)
    logits = constrain(logits, "batch", "seq", "vocab")
    aux = {k: v / cfg.n_layer for k, v in aux.items()}
    return (logits, aux) if return_aux else logits


def loss_fn(params: Params, batch: dict, cfg: MoEConfig) -> jax.Array:
    from ray_tpu.models.lm import cross_entropy, split_lm_batch

    inputs, targets = split_lm_batch(batch)
    logits, aux = forward(params, inputs, cfg, return_aux=True)
    ce = cross_entropy(logits, targets)
    return (ce + cfg.aux_loss_weight * aux["aux_loss"]
            + cfg.z_loss_weight * aux["z_loss"])


def num_params(cfg: MoEConfig) -> int:
    D, F, L, V, E = (cfg.d_model, cfg.d_ff, cfg.n_layer, cfg.vocab_size,
                     cfg.n_experts)
    kv_dim = cfg.n_kv_head * cfg.head_dim
    per_block = (D * D * 2 + D * kv_dim * 2 + D * E + E * 3 * D * F + 2 * D)
    total = V * D + L * per_block + D
    if not cfg.tie_embeddings:
        total += D * V
    return total


def active_params(cfg: MoEConfig) -> int:
    """Params touched per token (experts_per_token of n_experts)."""
    D, F, L = cfg.d_model, cfg.d_ff, cfg.n_layer
    kv_dim = cfg.n_kv_head * cfg.head_dim
    K = cfg.experts_per_token
    per_block = (D * D * 2 + D * kv_dim * 2 + D * cfg.n_experts
                 + K * 3 * D * F + 2 * D)
    total = cfg.vocab_size * D + L * per_block + D
    if not cfg.tie_embeddings:
        total += D * cfg.vocab_size
    return total
