"""Model families (pure JAX, TPU-first): gpt2, llama (GQA/RoPE/SwiGLU),
moe (Mixtral-style sparse MoE with expert parallelism)."""

from ray_tpu.models import gpt2

__all__ = ["gpt2", "llama", "moe"]


def __getattr__(name):
    if name in ("llama", "moe"):
        import importlib

        return importlib.import_module(f"ray_tpu.models.{name}")
    raise AttributeError(f"module 'ray_tpu.models' has no attribute {name!r}")
