from ray_tpu.models import gpt2

__all__ = ["gpt2"]
