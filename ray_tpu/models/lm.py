"""Shared language-model loss plumbing used by every model family."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def split_lm_batch(batch: dict):
    """{"tokens": [B,T+1]} or {"inputs","targets"} -> (inputs, targets)."""
    if "tokens" in batch:
        return batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    return batch["inputs"], batch["targets"]


def cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy; logits upcast to f32 for the softmax."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def resolve_attn_impl(attn_impl: str, seq_len: int) -> str:
    """Shared auto attention-implementation policy for all model families.

    auto → ring when the active mesh shards the sequence axis; else flash
    only where it measured faster than XLA's fused dense attention on TPU
    (v5e sweep 2026-07: dense wins through seq 1024; flash needs the T²
    score matrix to dominate) — dense otherwise.
    """
    if attn_impl != "auto":
        return attn_impl
    import jax

    from ray_tpu.parallel.mesh import current_mesh

    mesh = current_mesh()
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        return "ring"
    if (jax.default_backend() == "tpu" and seq_len >= 2048
            and seq_len % 128 == 0):
        return "flash"
    return "dense"
