"""Shared language-model loss plumbing used by every model family."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def split_lm_batch(batch: dict):
    """{"tokens": [B,T+1]} or {"inputs","targets"} -> (inputs, targets)."""
    if "tokens" in batch:
        return batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    return batch["inputs"], batch["targets"]


def cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy; logits upcast to f32 for the softmax."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def resolve_attn_impl(attn_impl: str, seq_len: int) -> str:
    """Shared auto attention-implementation policy for all model families.

    auto → ring when the active mesh shards the sequence axis; else flash
    where it MEASURED faster than XLA's fused dense attention on real
    TPU hardware — dense otherwise.

    Measured on v5e (axon relay, 2026-07 r5), both levels:
    - kernel fwd+bwd (B=4 H=12 Dh=64 bf16, benchmarks/
      FLASH_CROSSOVER.json): dense wins at 1024 (flash 0.93x), tie at
      2048 (0.99x), flash wins at 4096 (1.36x).
    - FULL 125M train step (bench.py sweep): flash 44.9k vs dense 42.9k
      tok/s/chip at T=2048 (+4.6%), 27.9k vs 16.9k at T=4096 (1.65x) —
      in-model, skipping the T² score materialization also relieves
      remat/HBM pressure, so flash breaks even EARLIER than the
      isolated kernel suggests.
    Crossover: flash from T >= 2048.
    """
    if attn_impl != "auto":
        return attn_impl
    import jax

    from ray_tpu.parallel.mesh import current_mesh

    mesh = current_mesh()
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        return "ring"
    if (jax.default_backend() == "tpu" and seq_len >= 2048
            and seq_len % 128 == 0):
        return "flash"
    return "dense"
