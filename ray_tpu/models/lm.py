"""Shared language-model loss plumbing used by every model family."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def split_lm_batch(batch: dict):
    """{"tokens": [B,T+1]} or {"inputs","targets"} -> (inputs, targets)."""
    if "tokens" in batch:
        return batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    return batch["inputs"], batch["targets"]


def cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy; logits upcast to f32 for the softmax."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)
