"""LLaMA model family in pure JAX, designed TPU-first.

Capability parity note: the reference serves LLaMA-family checkpoints through
vLLM (`python/ray/llm/_internal/serve/deployments/llm/vllm/`, SURVEY.md §2.5)
but ships no model math of its own. Here the framework owns the model: RMSNorm,
rotary embeddings, grouped-query attention, SwiGLU — all written the XLA way:

- stacked blocks (leading `n_layer` dim) + one `lax.scan` over them: one
  compiled block, O(1) compile time in depth;
- bfloat16 compute on the MXU, float32 params/softmax/reductions;
- logical-axis sharding annotations so the same code runs dp/fsdp/tp/sp
  sharded under any mesh from `ray_tpu.parallel.mesh.build_mesh`;
- GQA: `n_kv_head <= n_head` with K/V broadcast done via reshape (free under
  XLA) rather than materialized repeats;
- `jax.checkpoint` remat per block.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.mesh import constrain, logical_to_spec

Params = Any


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 32              # < n_head => grouped-query attention
    d_model: int = 4096
    d_ff: int = 11008                # SwiGLU hidden size
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    attn_impl: str = "auto"          # auto | dense | flash | ring | ulysses

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def q_per_kv(self) -> int:
        return self.n_head // self.n_kv_head

    @classmethod
    def preset(cls, name: str, **overrides) -> "LlamaConfig":
        presets = {
            "llama2-7b": dict(n_layer=32, n_head=32, n_kv_head=32,
                              d_model=4096, d_ff=11008, vocab_size=32000),
            "llama2-13b": dict(n_layer=40, n_head=40, n_kv_head=40,
                               d_model=5120, d_ff=13824, vocab_size=32000),
            "llama3-8b": dict(n_layer=32, n_head=32, n_kv_head=8,
                              d_model=4096, d_ff=14336, vocab_size=128256,
                              rope_theta=500000.0, max_seq_len=8192),
            "tinyllama-1.1b": dict(n_layer=22, n_head=32, n_kv_head=4,
                                   d_model=2048, d_ff=5632, vocab_size=32000),
            "llama-tiny": dict(n_layer=2, n_head=4, n_kv_head=2, d_model=128,
                               d_ff=352, vocab_size=512, max_seq_len=128),
        }
        return cls(**{**presets[name], **overrides})


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def init_params(key: jax.Array, cfg: LlamaConfig) -> Params:
    k_emb, k_head, k_blocks = jax.random.split(key, 3)
    pd = cfg.param_dtype
    D, Dh = cfg.d_model, cfg.head_dim
    kv_dim = cfg.n_kv_head * Dh
    std = 0.02
    resid_std = std / math.sqrt(2 * cfg.n_layer)

    def norm(k, shape, s=std):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(pd)

    def init_block(k):
        ks = jax.random.split(k, 7)
        return {
            "attn_norm": {"scale": jnp.ones((D,), pd)},
            "attn": {
                "wq": norm(ks[0], (D, D)),
                "wk": norm(ks[1], (D, kv_dim)),
                "wv": norm(ks[2], (D, kv_dim)),
                "wo": norm(ks[3], (D, D), resid_std),
            },
            "mlp_norm": {"scale": jnp.ones((D,), pd)},
            "mlp": {
                "wg": norm(ks[4], (D, cfg.d_ff)),
                "wu": norm(ks[5], (D, cfg.d_ff)),
                "wd": norm(ks[6], (cfg.d_ff, D), resid_std),
            },
        }

    blocks = jax.vmap(init_block)(jax.random.split(k_blocks, cfg.n_layer))
    params = {
        "wte": norm(k_emb, (cfg.vocab_size, D)),
        "blocks": blocks,
        "final_norm": {"scale": jnp.ones((D,), pd)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(k_head, (D, cfg.vocab_size))
    return params


def param_logical_axes(cfg: LlamaConfig) -> Params:
    block = {
        "attn_norm": {"scale": ("embed",)},
        "attn": {
            "wq": ("embed", "heads"),
            "wk": ("embed", "kv"),
            "wv": ("embed", "kv"),
            "wo": ("heads", "embed"),
        },
        "mlp_norm": {"scale": ("embed",)},
        "mlp": {
            "wg": ("embed", "mlp"),
            "wu": ("embed", "mlp"),
            "wd": ("mlp", "embed"),
        },
    }
    block = jax.tree.map(lambda axes: ("layers",) + axes, block,
                         is_leaf=lambda x: isinstance(x, tuple))
    axes = {
        "wte": ("vocab", "embed"),
        "blocks": block,
        "final_norm": {"scale": ("embed",)},
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def param_specs(cfg: LlamaConfig, rules=None) -> Params:
    return jax.tree.map(
        lambda axes: logical_to_spec(*axes, rules=rules),
        param_logical_axes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


# ---------------------------------------------------------------------------
# Building blocks (reused by ray_tpu.models.moe)
# ---------------------------------------------------------------------------

def rms_norm(x, p, eps: float):
    x32 = x.astype(jnp.float32)
    y = x32 * lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (y * p["scale"].astype(jnp.float32)).astype(x.dtype)


def rope_freqs(positions: jax.Array, head_dim: int, theta: float):
    """positions [...,T] int32 -> (cos, sin) each [...,T, head_dim/2] f32."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    ang = positions.astype(jnp.float32)[..., None] * inv
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, cos, sin):
    """x [..., T, n_head, head_dim]; cos/sin broadcastable [..., T, 1, hd/2]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _resolve_attn_impl(cfg, seq_len: int) -> str:
    impl = cfg.attn_impl
    from ray_tpu.models.lm import resolve_attn_impl

    return resolve_attn_impl(impl, seq_len)


def attention(x, p, cfg) -> jax.Array:
    """Causal GQA with RoPE. x [B,T,D]; p has wq/wk/wv/wo."""
    B, T, D = x.shape
    H, KV, Dh = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    q = (x @ p["wq"].astype(cfg.dtype)).reshape(B, T, H, Dh)
    k = (x @ p["wk"].astype(cfg.dtype)).reshape(B, T, KV, Dh)
    v = (x @ p["wv"].astype(cfg.dtype)).reshape(B, T, KV, Dh)

    cos, sin = rope_freqs(jnp.arange(T), Dh, cfg.rope_theta)
    cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    # GQA broadcast: [B,T,KV,Dh] -> [B,T,H,Dh] view; XLA fuses the broadcast
    # into the attention einsum, no materialized repeat.
    if KV != H:
        k = jnp.broadcast_to(k[:, :, :, None], (B, T, KV, cfg.q_per_kv, Dh)
                             ).reshape(B, T, H, Dh)
        v = jnp.broadcast_to(v[:, :, :, None], (B, T, KV, cfg.q_per_kv, Dh)
                             ).reshape(B, T, H, Dh)

    q = q.transpose(0, 2, 1, 3)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q = constrain(q, "batch", "heads", "seq", None)
    k = constrain(k, "batch", "heads", "seq", None)
    v = constrain(v, "batch", "heads", "seq", None)

    impl = _resolve_attn_impl(cfg, T)
    if impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        out = flash_attention(q, k, v, True)
    elif impl == "ring":
        from ray_tpu.ops.ring_attention import ring_attention

        out = ring_attention(q, k, v, causal=True)
    elif impl == "ulysses":
        from ray_tpu.ops.ring_attention import ulysses_attention

        out = ulysses_attention(q, k, v, causal=True)
    else:
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores / math.sqrt(Dh)
        causal = jnp.tril(jnp.ones((T, T), jnp.bool_))
        scores = jnp.where(causal[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, D)
    return out @ p["wo"].astype(cfg.dtype)


def swiglu(x, p, cfg) -> jax.Array:
    g = x @ p["wg"].astype(cfg.dtype)
    u = x @ p["wu"].astype(cfg.dtype)
    h = jax.nn.silu(g) * u
    h = constrain(h, "batch", "seq", "mlp")
    return h @ p["wd"].astype(cfg.dtype)


def _block(x, bp, cfg):
    x = x + attention(rms_norm(x, bp["attn_norm"], cfg.norm_eps), bp["attn"], cfg)
    x = constrain(x, "batch", "seq", "embed")
    x = x + swiglu(rms_norm(x, bp["mlp_norm"], cfg.norm_eps), bp["mlp"], cfg)
    x = constrain(x, "batch", "seq", "embed")
    return x


# ---------------------------------------------------------------------------
# Forward / loss
# ---------------------------------------------------------------------------

def embed(params: Params, tokens: jax.Array, cfg: LlamaConfig) -> jax.Array:
    x = params["wte"][tokens].astype(cfg.dtype)
    return constrain(x, "batch", "seq", "embed")


def unembed(params: Params, x: jax.Array, cfg: LlamaConfig) -> jax.Array:
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["wte"].T if cfg.tie_embeddings else params["lm_head"]
    logits = x @ head.astype(cfg.dtype)
    return constrain(logits, "batch", "seq", "vocab")


def forward(params: Params, tokens: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """tokens [B,T] int32 -> logits [B,T,vocab] (compute dtype)."""
    x = embed(params, tokens, cfg)

    block_fn = partial(_block, cfg=cfg)
    if cfg.remat:
        block_fn = jax.checkpoint(block_fn)

    x, _ = lax.scan(lambda c, bp: (block_fn(c, bp), None), x, params["blocks"])
    return unembed(params, x, cfg)


def loss_fn(params: Params, batch: dict, cfg: LlamaConfig) -> jax.Array:
    from ray_tpu.models.lm import cross_entropy, split_lm_batch

    inputs, targets = split_lm_batch(batch)
    return cross_entropy(forward(params, inputs, cfg), targets)


# ---------------------------------------------------------------------------
# KV-cache decode (serving path; GQA cache holds n_kv_head only)
# ---------------------------------------------------------------------------

def init_cache(cfg: LlamaConfig, batch: int, max_len: Optional[int] = None):
    T = max_len or cfg.max_seq_len
    shape = (cfg.n_layer, batch, cfg.n_kv_head, T, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def decode_step(params: Params, cache, tokens: jax.Array, pos: jax.Array,
                active: jax.Array, cfg: LlamaConfig):
    """One continuous-batch decode step (same contract as gpt2.decode_step):
    tokens [B] int32, pos [B] int32, active [B] bool ->
    (logits [B,vocab] f32, new_cache)."""
    B = tokens.shape[0]
    H, KV, Dh = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    T = cache["k"].shape[3]
    x = params["wte"][tokens].astype(cfg.dtype)               # [B, D]
    cos, sin = rope_freqs(pos, Dh, cfg.rope_theta)            # [B, Dh/2]

    def upd_one(c_b, val_b, p_b):
        return lax.dynamic_update_slice(c_b, val_b[:, None, :], (0, p_b, 0))

    def layer(x, scanned):
        bp, ck, cv = scanned
        h = rms_norm(x, bp["attn_norm"], cfg.norm_eps)
        q = (h @ bp["attn"]["wq"].astype(cfg.dtype)).reshape(B, H, Dh)
        k = (h @ bp["attn"]["wk"].astype(cfg.dtype)).reshape(B, KV, Dh)
        v = (h @ bp["attn"]["wv"].astype(cfg.dtype)).reshape(B, KV, Dh)
        q = apply_rope(q, cos[:, None, :], sin[:, None, :])
        k = apply_rope(k, cos[:, None, :], sin[:, None, :])
        ck_new = jax.vmap(upd_one)(ck, k, pos)
        cv_new = jax.vmap(upd_one)(cv, v, pos)
        ck = jnp.where(active[:, None, None, None], ck_new, ck)
        cv = jnp.where(active[:, None, None, None], cv_new, cv)
        # grouped scores: q [B, KV, G, Dh] against cache [B, KV, T, Dh]
        qg = q.reshape(B, KV, cfg.q_per_kv, Dh)
        scores = jnp.einsum("bkgd,bktd->bkgt", qg, ck,
                            preferred_element_type=jnp.float32)
        scores = scores / math.sqrt(Dh)
        t_idx = jnp.arange(T)[None, None, None, :]
        scores = jnp.where(t_idx <= pos[:, None, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = jnp.einsum("bkgt,bktd->bkgd", probs, cv).reshape(B, H * Dh)
        x = x + attn @ bp["attn"]["wo"].astype(cfg.dtype)
        h = rms_norm(x, bp["mlp_norm"], cfg.norm_eps)
        g = h @ bp["mlp"]["wg"].astype(cfg.dtype)
        u = h @ bp["mlp"]["wu"].astype(cfg.dtype)
        x = x + (jax.nn.silu(g) * u) @ bp["mlp"]["wd"].astype(cfg.dtype)
        return x, (ck, cv)

    x, (new_k, new_v) = lax.scan(layer, x,
                                 (params["blocks"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["wte"].T if cfg.tie_embeddings else params["lm_head"]
    logits = (x @ head.astype(cfg.dtype)).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def num_params(cfg: LlamaConfig) -> int:
    D, F, L, V = cfg.d_model, cfg.d_ff, cfg.n_layer, cfg.vocab_size
    kv_dim = cfg.n_kv_head * cfg.head_dim
    per_block = D * D * 2 + D * kv_dim * 2 + 3 * D * F + 2 * D
    total = V * D + L * per_block + D
    if not cfg.tie_embeddings:
        total += D * V
    return total
