"""GPT-2 family in pure JAX, designed TPU-first.

Capability target: the reference's north-star config "Ray Train GPT-2-125M
data-parallel" (/root/repo/BASELINE.json) — but built the XLA way rather than
as a torch port:

- layers are *stacked* (leading `n_layer` dim on every block param) and the
  forward pass is a single `lax.scan` over them: one compiled block, O(1)
  compile time in depth, and XLA can pipeline HBM prefetch of layer weights;
- compute in bfloat16 (MXU-native), params + softmax/loss in float32;
- every activation is annotated with logical axes (`batch`/`seq`/`embed`/...)
  so the same code runs dp/fsdp/tp/sp sharded under any mesh from
  `ray_tpu.parallel.mesh.build_mesh` — XLA inserts the ICI collectives;
- `jax.checkpoint` (remat) around each block trades FLOPs for HBM.

No dropout in round 1 (the reference benchmark config trains without it).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.mesh import constrain, logical_to_spec

Params = Any  # nested dict pytree


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50304          # GPT-2's 50257 padded up to a 128 multiple (MXU tiling)
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 3072
    max_seq_len: int = 1024
    dtype: Any = jnp.bfloat16        # activation/compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = True
    # rematerialization policy: "full" recomputes everything in the bwd
    # pass; "dots" saves matmul outputs (jax dots_with_no_batch_dims
    # policy) — most of remat=False's speed at a fraction of the memory
    remat_policy: str = "full"
    # attention implementation: auto | dense | flash (pallas) | ring | ulysses
    # auto: ring when the active mesh has sp>1, flash on TPU, dense otherwise
    attn_impl: str = "auto"
    # chunked fused cross-entropy: unembed+CE computed per ce_chunk-token
    # slice under jax.checkpoint, so the [B,T,V] logits (the single
    # largest training buffer — ~3 GB at 350M/b14) never materialize;
    # backward recomputes each chunk's logits. 0 = off (plain unembed+CE)
    ce_chunk: int = 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @classmethod
    def preset(cls, name: str, **overrides) -> "GPT2Config":
        presets = {
            "gpt2-125m": dict(n_layer=12, n_head=12, d_model=768, d_ff=3072),
            "gpt2-350m": dict(n_layer=24, n_head=16, d_model=1024, d_ff=4096),
            "gpt2-774m": dict(n_layer=36, n_head=20, d_model=1280, d_ff=5120),
            "gpt2-1.5b": dict(n_layer=48, n_head=25, d_model=1600, d_ff=6400),
            "gpt2-tiny": dict(n_layer=2, n_head=4, d_model=128, d_ff=512,
                              vocab_size=512, max_seq_len=128),
        }
        return cls(**{**presets[name], **overrides})


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def init_params(key: jax.Array, cfg: GPT2Config) -> Params:
    """GPT-2 init: N(0, 0.02), residual projections scaled by 1/sqrt(2*n_layer)."""
    k_wte, k_wpe, k_blocks = jax.random.split(key, 3)
    std = 0.02
    resid_std = std / math.sqrt(2 * cfg.n_layer)
    pd = cfg.param_dtype

    def norm(k, shape, s):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(pd)

    def init_block(k):
        ks = jax.random.split(k, 4)
        return {
            "ln1": {"scale": jnp.ones((cfg.d_model,), pd),
                    "bias": jnp.zeros((cfg.d_model,), pd)},
            "attn": {
                "wqkv": norm(ks[0], (cfg.d_model, 3 * cfg.d_model), std),
                "bqkv": jnp.zeros((3 * cfg.d_model,), pd),
                "wo": norm(ks[1], (cfg.d_model, cfg.d_model), resid_std),
                "bo": jnp.zeros((cfg.d_model,), pd),
            },
            "ln2": {"scale": jnp.ones((cfg.d_model,), pd),
                    "bias": jnp.zeros((cfg.d_model,), pd)},
            "mlp": {
                "wi": norm(ks[2], (cfg.d_model, cfg.d_ff), std),
                "bi": jnp.zeros((cfg.d_ff,), pd),
                "wo": norm(ks[3], (cfg.d_ff, cfg.d_model), resid_std),
                "bo": jnp.zeros((cfg.d_model,), pd),
            },
        }

    blocks = jax.vmap(init_block)(jax.random.split(k_blocks, cfg.n_layer))
    return {
        "wte": norm(k_wte, (cfg.vocab_size, cfg.d_model), std),
        "wpe": norm(k_wpe, (cfg.max_seq_len, cfg.d_model), std / 2),
        "blocks": blocks,
        "ln_f": {"scale": jnp.ones((cfg.d_model,), pd),
                 "bias": jnp.zeros((cfg.d_model,), pd)},
    }


def param_logical_axes(cfg: GPT2Config) -> Params:
    """Logical axis names per param leaf (same tree structure as init_params).

    Resolve to PartitionSpecs with `param_specs`. Conventions: `embed` is the
    ZeRO/fsdp-sharded hidden axis, `mlp`/`heads`-shaped output dims shard over
    tp, `vocab` over tp (tied embedding => logits matmul is tp-sharded).
    """
    del cfg
    block = {
        "ln1": {"scale": ("embed",), "bias": ("embed",)},
        "attn": {
            "wqkv": ("embed", "heads"),   # 3*d_model output dim, megatron col-parallel
            "bqkv": ("heads",),
            "wo": ("heads", "embed"),     # row-parallel back to hidden
            "bo": ("embed",),
        },
        "ln2": {"scale": ("embed",), "bias": ("embed",)},
        "mlp": {
            "wi": ("embed", "mlp"),
            "bi": ("mlp",),
            "wo": ("mlp", "embed"),
            "bo": ("embed",),
        },
    }
    # stacked layer dim is logical axis "layers" (unsharded by default)
    block = jax.tree.map(lambda axes: ("layers",) + axes, block,
                         is_leaf=lambda x: isinstance(x, tuple))
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": block,
        "ln_f": {"scale": ("embed",), "bias": ("embed",)},
    }


def param_specs(cfg: GPT2Config, rules=None) -> Params:
    """PartitionSpec pytree for the params under the active (or given) rules."""
    return jax.tree.map(
        lambda axes: logical_to_spec(*axes, rules=rules),
        param_logical_axes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _layer_norm(x, p, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * lax.rsqrt(var + eps)
    return (y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)).astype(x.dtype)


def _resolve_attn_impl(cfg: GPT2Config, seq_len: int) -> str:
    from ray_tpu.models.lm import resolve_attn_impl

    return resolve_attn_impl(cfg.attn_impl, seq_len)


def _attention(x, p, cfg: GPT2Config):
    B, T, D = x.shape
    H, Dh = cfg.n_head, cfg.head_dim
    qkv = x @ p["wqkv"].astype(cfg.dtype) + p["bqkv"].astype(cfg.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)
    q = constrain(q, "batch", "heads", "seq", None)
    k = constrain(k, "batch", "heads", "seq", None)
    v = constrain(v, "batch", "heads", "seq", None)

    impl = _resolve_attn_impl(cfg, T)
    if impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        out = flash_attention(q, k, v, True)
    elif impl == "ring":
        from ray_tpu.ops.ring_attention import ring_attention

        out = ring_attention(q, k, v, causal=True)
    elif impl == "ulysses":
        from ray_tpu.ops.ring_attention import ulysses_attention

        out = ulysses_attention(q, k, v, causal=True)
    else:
        # fp32 softmax for stability; scores computed on MXU in bf16 inputs.
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
        scores = scores / math.sqrt(Dh)
        causal = jnp.tril(jnp.ones((T, T), jnp.bool_))
        scores = jnp.where(causal[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, D)
    out = out @ p["wo"].astype(cfg.dtype) + p["bo"].astype(cfg.dtype)
    return out


def _mlp(x, p, cfg: GPT2Config):
    h = x @ p["wi"].astype(cfg.dtype) + p["bi"].astype(cfg.dtype)
    h = constrain(h, "batch", "seq", "mlp")
    h = jax.nn.gelu(h, approximate=True)
    return h @ p["wo"].astype(cfg.dtype) + p["bo"].astype(cfg.dtype)


def _block(x, bp, cfg: GPT2Config):
    x = x + _attention(_layer_norm(x, bp["ln1"]), bp["attn"], cfg)
    x = constrain(x, "batch", "seq", "embed")
    x = x + _mlp(_layer_norm(x, bp["ln2"]), bp["mlp"], cfg)
    x = constrain(x, "batch", "seq", "embed")
    return x


def embed(params: Params, tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    """tokens [B,T] int32 -> embeddings [B,T,D] (compute dtype)."""
    T = tokens.shape[1]
    # lookup against an explicitly replicated table view: gathering from a
    # ZeRO-sharded (embed->fsdp) table makes the output inherit the
    # table's layout and forces the partitioner into an involuntary full
    # rematerialization when re-sharding to the batch layout; an upfront
    # all-gather of the table (the ZeRO-3 prefetch pattern) is the cheap
    # and intended collective
    wte = constrain(params["wte"], None, None)
    x = wte[tokens] + params["wpe"][:T][None]
    return constrain(x.astype(cfg.dtype), "batch", "seq", "embed")


def unembed(params: Params, x: jax.Array, cfg: GPT2Config) -> jax.Array:
    """final hidden [B,T,D] -> logits [B,T,vocab] (tied embeddings)."""
    x = _layer_norm(x, params["ln_f"])
    logits = x @ params["wte"].T.astype(cfg.dtype)
    return constrain(logits, "batch", "seq", "vocab")


def hidden_states(params: Params, tokens: jax.Array,
                  cfg: GPT2Config) -> jax.Array:
    """tokens [B, T] int32 -> final hidden [B, T, D] (pre-unembed)."""
    x = embed(params, tokens, cfg)

    block_fn = partial(_block, cfg=cfg)
    if cfg.remat:
        policies = {
            "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            "dots_all": jax.checkpoint_policies.dots_saveable,
        }
        policy = policies.get(cfg.remat_policy)
        block_fn = (jax.checkpoint(block_fn, policy=policy) if policy
                    else jax.checkpoint(block_fn))

    def scan_body(carry, bp):
        return block_fn(carry, bp), None

    x, _ = lax.scan(scan_body, x, params["blocks"])
    return x


def forward(params: Params, tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, vocab] (compute dtype)."""
    return unembed(params, hidden_states(params, tokens, cfg), cfg)


def chunked_ce(params: Params, x: jax.Array, targets: jax.Array,
               cfg: GPT2Config) -> jax.Array:
    """Fused unembed + cross-entropy over seq chunks: peak logits memory
    drops from [B, T, V] to [B, ce_chunk, V] (fwd AND bwd — the chunk
    body is rematerialized), freeing HBM for larger per-chip batches.
    Numerically identical to unembed+cross_entropy (f32 reductions)."""
    x = _layer_norm(x, params["ln_f"])
    W = params["wte"].T.astype(cfg.dtype)                  # [D, V]
    B, T, D = x.shape
    C = cfg.ce_chunk
    if T % C:
        raise ValueError(f"seq len {T} not divisible by ce_chunk={C}")
    K = T // C
    xc = x.reshape(B, K, C, D).swapaxes(0, 1)              # [K, B, C, D]
    tc = targets.reshape(B, K, C).swapaxes(0, 1)           # [K, B, C]

    def body(acc, xt):
        xcb, tcb = xt
        logits = constrain(xcb @ W, "batch", "seq", "vocab")
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tcb[..., None],
                                   axis=-1)[..., 0]
        return acc + jnp.sum(logz - gold), None

    total, _ = lax.scan(jax.checkpoint(body), jnp.float32(0.0), (xc, tc))
    return total / (B * T)


def loss_fn(params: Params, batch: dict, cfg: GPT2Config) -> jax.Array:
    """Next-token cross-entropy. batch = {"tokens": [B,T+1] int32} or
    {"inputs": [B,T], "targets": [B,T]}."""
    from ray_tpu.models.lm import cross_entropy, split_lm_batch

    inputs, targets = split_lm_batch(batch)
    if cfg.ce_chunk:
        return chunked_ce(params, hidden_states(params, inputs, cfg),
                          targets, cfg)
    return cross_entropy(forward(params, inputs, cfg), targets)


# ---------------------------------------------------------------------------
# KV-cache decode (serving path)
# ---------------------------------------------------------------------------

def init_cache(cfg: GPT2Config, batch: int, max_len: Optional[int] = None):
    """Per-layer KV cache: {"k","v"}: [n_layer, B, H, T, Dh] (compute dtype)."""
    T = max_len or cfg.max_seq_len
    shape = (cfg.n_layer, batch, cfg.n_head, T, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def decode_step(params: Params, cache, tokens: jax.Array, pos: jax.Array,
                active: jax.Array, cfg: GPT2Config):
    """One decode step for a continuous batch.

    tokens [B] int32 (current input token per slot), pos [B] int32 (its
    position), active [B] bool (slots whose cache should advance). Returns
    (logits [B, vocab] f32, new_cache). Inactive slots' caches are untouched
    and their logits are garbage — the engine masks them.
    """
    B = tokens.shape[0]
    H, Dh = cfg.n_head, cfg.head_dim
    T = cache["k"].shape[3]
    wte = params["wte"]
    x = wte[tokens] + params["wpe"][jnp.clip(pos, 0, cfg.max_seq_len - 1)]
    x = x.astype(cfg.dtype)                                   # [B, D]

    def upd_one(c_b, val_b, p_b):
        # c_b [H, T, Dh], val_b [H, Dh] -> write at position p_b
        return jax.lax.dynamic_update_slice(
            c_b, val_b[:, None, :], (0, p_b, 0))

    def layer(x, scanned):
        bp, ck, cv = scanned                                  # ck/cv [B,H,T,Dh]
        h = _layer_norm(x, bp["ln1"])
        qkv = h @ bp["attn"]["wqkv"].astype(cfg.dtype) + \
            bp["attn"]["bqkv"].astype(cfg.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, H, Dh)
        k = k.reshape(B, H, Dh)
        v = v.reshape(B, H, Dh)
        ck_new = jax.vmap(upd_one)(ck, k, pos)
        cv_new = jax.vmap(upd_one)(cv, v, pos)
        ck = jnp.where(active[:, None, None, None], ck_new, ck)
        cv = jnp.where(active[:, None, None, None], cv_new, cv)
        scores = jnp.einsum("bhd,bhtd->bht", q, ck,
                            preferred_element_type=jnp.float32)
        scores = scores / math.sqrt(Dh)
        t_idx = jnp.arange(T)[None, None, :]
        scores = jnp.where(t_idx <= pos[:, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = jnp.einsum("bht,bhtd->bhd", probs, cv)
        attn = attn.reshape(B, H * Dh)
        attn = attn @ bp["attn"]["wo"].astype(cfg.dtype) + \
            bp["attn"]["bo"].astype(cfg.dtype)
        x = x + attn
        x = x + _mlp(_layer_norm(x, bp["ln2"]), bp["mlp"], cfg)
        return x, (ck, cv)

    x, (new_k, new_v) = lax.scan(layer, x,
                                 (params["blocks"], cache["k"], cache["v"]))
    x = _layer_norm(x, params["ln_f"])
    logits = (x @ wte.T.astype(cfg.dtype)).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def prefill_chunk(params: Params, cache, tokens: jax.Array, pos0: jax.Array,
                  length: jax.Array, active: jax.Array, cfg: GPT2Config):
    """Process up to C prompt tokens per slot in ONE fused step (chunked
    prefill for the continuous-batching engine: a long prompt advances C
    positions per engine tick instead of 1, while decode slots ride along
    as length-1 lanes).

    tokens [B, C] int32 (left-aligned chunk per slot), pos0 [B] int32 (the
    chunk's first cache position), length [B] int32 (valid tokens in the
    chunk, 0..C), active [B] bool. Returns (logits [B, vocab] taken at
    each slot's LAST valid chunk token, new_cache). Inactive/zero-length
    slots' caches are untouched and their logits are garbage. Cache
    writes are lane-masked read-modify-writes: dynamic_update_slice
    clamps its start near the sequence end, so an unmasked block write
    would smear garbage lanes over valid earlier positions. Callers
    guarantee pos0 + length <= T and C <= T.
    """
    B, C = tokens.shape
    H, Dh = cfg.n_head, cfg.head_dim
    T = cache["k"].shape[3]
    wte = params["wte"]
    lane = jnp.arange(C)
    pos = pos0[:, None] + lane[None, :]                           # [B, C]
    valid = lane[None, :] < length[:, None]                       # [B, C]
    x = wte[tokens] + params["wpe"][jnp.clip(pos, 0, cfg.max_seq_len - 1)]
    x = x.astype(cfg.dtype)                                       # [B, C, D]

    def upd_chunk(c_b, val_b, p0_b, valid_b):
        # c_b [H, T, Dh], val_b [H, C, Dh]: write val lane i at position
        # p0_b + i for VALID lanes only. Window lane w (at absolute
        # position start + w) takes val lane w - off, where off is the
        # clamp shift; everything else keeps the old cache content.
        start = jnp.clip(p0_b, 0, T - C)
        off = p0_b - start
        old = jax.lax.dynamic_slice(c_b, (0, start, 0), (H, C, Dh))
        src = lane - off
        srcc = jnp.clip(src, 0, C - 1)
        take = (src >= 0) & (src < C) & valid_b[srcc]
        blended = jnp.where(take[None, :, None], val_b[:, srcc, :], old)
        return jax.lax.dynamic_update_slice(c_b, blended, (0, start, 0))

    def layer(x, scanned):
        bp, ck, cv = scanned                                # ck/cv [B,H,T,Dh]
        h = _layer_norm(x, bp["ln1"])
        qkv = h @ bp["attn"]["wqkv"].astype(cfg.dtype) + \
            bp["attn"]["bqkv"].astype(cfg.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, C, H, Dh).transpose(0, 2, 1, 3)    # [B, H, C, Dh]
        k = k.reshape(B, C, H, Dh).transpose(0, 2, 1, 3)
        v = v.reshape(B, C, H, Dh).transpose(0, 2, 1, 3)
        ck_new = jax.vmap(upd_chunk)(ck, k, pos0, valid)
        cv_new = jax.vmap(upd_chunk)(cv, v, pos0, valid)
        ck = jnp.where(active[:, None, None, None], ck_new, ck)
        cv = jnp.where(active[:, None, None, None], cv_new, cv)
        # chunk lanes attend to everything written up to their own
        # position (the chunk's k/v are already in the cache, so this is
        # causal intra-chunk attention + full attention to the prefix)
        scores = jnp.einsum("bhcd,bhtd->bhct", q, ck,
                            preferred_element_type=jnp.float32)
        scores = scores / math.sqrt(Dh)
        t_idx = jnp.arange(T)[None, None, None, :]
        scores = jnp.where(t_idx <= pos[:, None, :, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = jnp.einsum("bhct,bhtd->bhcd", probs, cv)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, C, H * Dh)
        attn = attn @ bp["attn"]["wo"].astype(cfg.dtype) + \
            bp["attn"]["bo"].astype(cfg.dtype)
        x = x + attn
        x = x + _mlp(_layer_norm(x, bp["ln2"]), bp["mlp"], cfg)
        return x, (ck, cv)

    x, (new_k, new_v) = lax.scan(layer, x,
                                 (params["blocks"], cache["k"], cache["v"]))
    last = jnp.clip(length - 1, 0, C - 1)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
    x_last = _layer_norm(x_last, params["ln_f"])
    logits = (x_last @ wte.T.astype(cfg.dtype)).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def num_params(cfg: GPT2Config) -> int:
    d, f, L, V, S = cfg.d_model, cfg.d_ff, cfg.n_layer, cfg.vocab_size, cfg.max_seq_len
    per_block = (3 * d * d + 3 * d) + (d * d + d) + (2 * d * f + f + d) + 4 * d
    return V * d + S * d + L * per_block + 2 * d


def flops_per_token(cfg: GPT2Config, seq_len: int) -> float:
    """Approx training FLOPs/token (fwd+bwd ≈ 6*N + attention term)."""
    n = num_params(cfg) - cfg.vocab_size * cfg.d_model  # non-embedding
    attn = 12 * cfg.n_layer * cfg.d_model * seq_len
    return 6 * (n + cfg.vocab_size * cfg.d_model) + attn


# ---------------------------------------------------------------------------
# Checkpoint IO (serve real weights: the reference's serve.llm loads HF
# checkpoints into its engines; here trained params round-trip through an
# npz so Serve replicas host what the trainer produced, not random init)
# ---------------------------------------------------------------------------

_CFG_FIELDS = ("vocab_size", "n_layer", "n_head", "d_model", "d_ff",
               "max_seq_len")


def save_params(path: str, params: Params, cfg: GPT2Config) -> str:
    """Write params + the architecture fields needed to rebuild them.
    One npz (path-keyed flat pytree) + a json sidecar; no orbax needed
    for single-host serving checkpoints."""
    import json
    import os

    import numpy as np

    os.makedirs(path, exist_ok=True)
    flat = {}
    for kp, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                       for k in kp)
        flat[key] = np.asarray(leaf)
    tmp = os.path.join(path, "params.npz.tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **flat)
    os.replace(tmp, os.path.join(path, "params.npz"))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({k: getattr(cfg, k) for k in _CFG_FIELDS}, f)
    return path


def load_params(path: str, cfg: Optional[GPT2Config] = None
                ) -> Tuple[Params, GPT2Config]:
    """Load a save_params checkpoint; architecture comes from the sidecar
    (runtime knobs like remat/attn_impl come from `cfg` when given)."""
    import json
    import os

    import numpy as np

    with open(os.path.join(path, "config.json")) as f:
        arch = json.load(f)
    base = cfg or GPT2Config()
    cfg = dataclasses.replace(base, **arch)
    template = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    leaves_kp = jax.tree_util.tree_flatten_with_path(template)[0]
    with np.load(os.path.join(path, "params.npz")) as z:
        loaded = []
        for kp, leaf in leaves_kp:
            key = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                           for k in kp)
            arr = z[key]
            if tuple(arr.shape) != tuple(leaf.shape):
                raise ValueError(f"checkpoint leaf {key}: shape "
                                 f"{arr.shape} != expected {leaf.shape}")
            loaded.append(jnp.asarray(arr, dtype=leaf.dtype))
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, loaded), cfg


# ---------------------------------------------------------------------------
# LoRA adapters
# ---------------------------------------------------------------------------
def apply_lora(params: Params, adapter: dict) -> Params:
    """Merge low-rank adapters into a COPY of `params`.

    `adapter`: {"dotted.path": {"A": [..., D, r], "B": [..., r, K],
    "alpha": float}} — delta = (alpha / r) * A @ B, the standard LoRA
    scaling. Stacked scanned-layer params ([L, D, K]) take stacked
    A/B ([L, D, r], [L, r, K]) via batched matmul. Serving keeps the
    BASE params shared; each adapter costs only its merged copies of the
    targeted leaves (reference: multi-LoRA serving behind serve.llm)."""
    out = jax.tree.map(lambda x: x, params)  # shallow structural copy
    for path, spec in adapter.items():
        keys = path.split(".")
        node = out
        for k in keys[:-1]:
            node[k] = dict(node[k]) if isinstance(node[k], dict) else node[k]
            node = node[k]
        leaf = node[keys[-1]]
        A = jnp.asarray(spec["A"], leaf.dtype)
        B = jnp.asarray(spec["B"], leaf.dtype)
        r = A.shape[-1]
        alpha = float(spec.get("alpha", r))
        delta = (alpha / r) * (A @ B)
        if delta.shape != leaf.shape:
            raise ValueError(
                f"LoRA delta shape {delta.shape} != param {leaf.shape} "
                f"at {path!r}")
        node[keys[-1]] = leaf + delta
    return out


def load_lora_npz(path: str) -> dict:
    """Adapter file: npz with `<dotted.path>.A`, `<dotted.path>.B` and
    optional `<dotted.path>.alpha` entries (local path or fsspec URI)."""
    import numpy as _np

    from ray_tpu.utils import fs as _fs

    with _fs.open(path, "rb") as f:
        data = _np.load(f)
        adapter: dict = {}
        for name in data.files:
            base, _, kind = name.rpartition(".")
            if kind not in ("A", "B", "alpha"):
                continue
            adapter.setdefault(base, {})[kind] = data[name]
    missing = [k for k, v in adapter.items() if "A" not in v or "B" not in v]
    if missing:
        raise ValueError(f"LoRA entries missing A/B pairs: {missing}")
    return adapter
