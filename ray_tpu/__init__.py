"""ray_tpu: TPU-native distributed AI runtime.

A brand-new framework with the capabilities of the reference (Ray): task/actor
core runtime, placement groups, collectives, Train/Serve/Data/Tune libraries —
re-architected around JAX/XLA/pjit/Pallas and TPU pod scheduling.
"""

from ray_tpu._version import version as __version__

# Core runtime API is imported lazily so that pure-compute users (models/ops/
# parallel) don't pay for it, and vice versa.
_CORE_API = (
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "method", "get_runtime_context", "nodes", "get_actor",
    "available_resources", "cluster_resources", "ObjectRef", "actor", "free",
    "put_device",
)


def __getattr__(name):
    if name in _CORE_API:
        from ray_tpu.core import api as _api

        return getattr(_api, name)
    if name == "timeline":
        from ray_tpu.util.timeline import timeline

        return timeline
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_CORE_API))
