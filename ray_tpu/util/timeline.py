"""`ray_tpu.timeline()`: Chrome-trace dump of task execution.

Parity: the `ray timeline` CLI (`python/ray/scripts/scripts.py`) which turns
profile events into a chrome://tracing JSON file. Here RUNNING→FINISHED/
FAILED transitions from the head's task-event buffer become complete ("X")
trace events, one row per worker.

With tracing enabled, the driver's flight-recorder scheduling phases are
merged in: each traced task gets its own row showing submit →
lease-acquire[local|peer|spillback|head] → dispatch → run as distinct
sub-spans ("peer" = a daemon-referred grant completed at a peer
daemon's warm pool; "parked" submits mark cold tasks that waited in the
client-local dispatch queue), with Chrome flow arrows (`s`/`f` events
keyed by task id) connecting submit to the run slice — the two-level
scheduler's warm path made visible per task.

A `head-reconcile` row renders the head's reconciliation phases from the
merged lease-event stream: node_dead→reregister/pool_reconcile windows,
head_lost→head_reconnect outages, stale-epoch rejects, and the train
controller's group_start/death_detected/restore/resize spans.
"""

from __future__ import annotations

import json
from typing import List, Optional


def _sched_phase_events(trace: List[dict]) -> None:
    """Append the driver-side scheduling-phase events (flight recorder)
    for traced tasks; no-op when nothing was recorded."""
    from ray_tpu.core.api import _global_client, is_initialized

    if not is_initialized():
        return
    client = _global_client()
    events = list(getattr(client, "sched_events", ()) or ())
    flows = {}   # task_id -> phases seen (for flow arrows)
    for ev in events:
        t0, t1 = ev.get("t0"), ev.get("t1")
        if t0 is None or t1 is None:
            continue
        task_id = ev.get("task_id")
        mode = ev.get("mode")
        phase = ev["phase"]
        name = phase if phase in ("submit", "dispatch", "run") else \
            f"{phase}[{mode}]"
        tid = task_id[:12] if task_id else "lease-pool"
        trace.append({
            "name": name, "cat": "sched", "ph": "X",
            # floor at 0.1µs: sub-resolution phases must stay visible (and
            # nonzero) in chrome://tracing
            "ts": t0 * 1e6, "dur": max(t1 - t0, 1e-7) * 1e6,
            "pid": "driver-sched", "tid": tid,
            "args": {k: v for k, v in ev.items()
                     if k not in ("t0", "t1") and v is not None},
        })
        if task_id:
            flows.setdefault(task_id, {})[phase] = ev
    # flow arrows: submit → run (falling back to dispatch) per task
    for task_id, phases in flows.items():
        src = phases.get("submit")
        dst = phases.get("run") or phases.get("dispatch")
        if src is None or dst is None:
            continue
        common = {"cat": "sched", "name": "sched-flow", "id": task_id,
                  "pid": "driver-sched", "tid": task_id[:12]}
        trace.append({**common, "ph": "s", "ts": src["t1"] * 1e6})
        trace.append({**common, "ph": "f", "bp": "e",
                      "ts": dst["t0"] * 1e6})


def _reconcile_phase_events(trace: List[dict]) -> None:
    """Head-side reconciliation phases from the merged flight-recorder
    lease-event stream: epoch-fence / pool-reconcile windows and train
    controller restarts become spans so 'why did the cluster pause here'
    is answerable from the same trace as the task rows. Best-effort —
    a head that predates these event kinds just contributes nothing."""
    from ray_tpu.util.state import list_lease_events

    try:
        events = list_lease_events()
    except Exception:
        return
    PID = "head-reconcile"
    # windows opened by a loss event, closed by the matching recovery
    open_windows = {}   # (kind_family, node_id) -> open event
    pairs = {"node_dead": ("node_reregister", "pool_reconcile"),
             "head_lost": ("head_reconnect",)}
    closers = {c: fam for fam, cs in pairs.items() for c in cs}
    for ev in events:
        kind = ev.get("kind", "")
        nid = (ev.get("node_id") or "")[:12]
        if kind in pairs:
            open_windows[(kind, nid)] = ev
            continue
        if kind in closers:
            fam = closers[kind]
            start = open_windows.pop((fam, nid), None)
            if start is not None:
                trace.append({
                    "name": f"{fam}→{kind}", "cat": "reconcile", "ph": "X",
                    "ts": start["ts"] * 1e6,
                    "dur": max(ev["ts"] - start["ts"], 1e-7) * 1e6,
                    "pid": PID, "tid": nid or "head",
                    "args": {"node_id": ev.get("node_id")}})
            continue
        if kind.startswith("train_"):
            t0, t1 = ev.get("t0"), ev.get("t1")
            row = {"cat": "train", "pid": PID,
                   "tid": f"train:{ev.get('run', '?')}",
                   "args": {k: v for k, v in ev.items()
                            if k not in ("t0", "t1") and v is not None}}
            if t0 is not None and t1 is not None:
                trace.append({**row, "name": kind, "ph": "X",
                              "ts": t0 * 1e6,
                              "dur": max(t1 - t0, 1e-7) * 1e6})
            else:
                trace.append({**row, "name": kind, "ph": "i",
                              "ts": ev["ts"] * 1e6, "s": "t"})
            continue
        if kind in ("chain_fence", "chain_failover"):
            # compiled serve plane: a fence (replica death / ring failure)
            # and any failover burst render as instants on the chain's
            # own reconcile lane, next to the scheduler's node_dead
            # windows they usually coincide with
            trace.append({
                "name": kind, "cat": "reconcile", "ph": "i",
                "ts": ev["ts"] * 1e6, "s": "t", "pid": PID,
                "tid": f"chain:{ev.get('chain', '?')}",
                "args": {k: v for k, v in ev.items()
                         if k != "ts" and v is not None}})
            continue
        if kind == "stale_epoch":
            trace.append({
                "name": "stale_epoch", "cat": "reconcile", "ph": "i",
                "ts": ev["ts"] * 1e6, "s": "t", "pid": PID,
                "tid": nid or "head",
                "args": {"method": ev.get("method"),
                         "epoch": ev.get("epoch")}})
    # still-open windows (node died, never came back): begin events
    for (fam, nid), start in open_windows.items():
        trace.append({"name": fam, "cat": "reconcile", "ph": "B",
                      "ts": start["ts"] * 1e6, "pid": PID,
                      "tid": nid or "head",
                      "args": {"node_id": start.get("node_id")}})


def _workload_span_events(trace: List[dict]) -> None:
    """Merge the workload flight recorder's spans — serve request spans,
    replica/actor execution, object pulls, collective ops, train steps —
    into the trace: the driver's own finished spans plus every span other
    processes pushed to the head, deduped by span id. pid = node,
    tid = process; Chrome flow arrows (`s`/`f`, keyed by the child span
    id) connect parent→child across process boundaries so one request's
    proxy → replica → task path reads as one connected lane."""
    spans = {}
    # label local spans exactly as the head labels this process's pushed
    # copies, and let head copies win on overlap — otherwise one process
    # renders as two lanes ("driver" + its worker id) with false
    # cross-process flow arrows between them
    proc = node = "driver"
    try:
        from ray_tpu.core.api import _global_client, is_initialized

        if is_initialized():
            client = _global_client()
            proc = client.worker_id.hex()[:12]
            nid = (client.node_info or {}).get("node_id")
            if nid is not None:
                node = nid.hex()[:12]
    except Exception:
        pass
    try:
        from ray_tpu.util import tracing

        for s in tracing.get_finished_spans():
            spans[s.span_id] = {**s.to_dict(), "proc": proc, "node": node}
    except Exception:
        pass
    try:
        from ray_tpu.util.state import list_trace_spans

        for row in list_trace_spans():
            if row.get("span_id"):
                spans[row["span_id"]] = row
    except Exception:
        pass

    def _pid(sd):
        return sd.get("node") or sd.get("proc") or "?"

    def _tid(sd):
        return sd.get("proc") or "?"

    for sd in spans.values():
        start, end = sd.get("start_ts"), sd.get("end_ts")
        if not start:
            continue
        trace.append({
            "name": sd.get("name", "span"), "cat": "span", "ph": "X",
            "ts": start * 1e6,
            "dur": max((end or start) - start, 1e-7) * 1e6,
            "pid": _pid(sd), "tid": _tid(sd),
            "args": {"trace_id": sd.get("trace_id"),
                     "span_id": sd.get("span_id"),
                     "parent_id": sd.get("parent_id"),
                     **(sd.get("attributes") or {})},
        })
    # flow arrows only where BOTH ends exist (every flow event must pair)
    for sd in spans.values():
        parent = spans.get(sd.get("parent_id"))
        if parent is None or not sd.get("start_ts") \
                or not parent.get("start_ts"):
            continue
        if _pid(parent) == _pid(sd) and _tid(parent) == _tid(sd):
            continue  # same lane: nesting is already visible
        common = {"cat": "span-flow", "name": "trace-flow",
                  "id": sd["span_id"]}
        trace.append({**common, "ph": "s", "pid": _pid(parent),
                      "tid": _tid(parent),
                      "ts": parent["start_ts"] * 1e6})
        trace.append({**common, "ph": "f", "bp": "e", "pid": _pid(sd),
                      "tid": _tid(sd),
                      "ts": max(sd["start_ts"], parent["start_ts"]) * 1e6})


def timeline(filename: Optional[str] = None, *,
             format: Optional[str] = None) -> List[dict]:
    """Build Chrome trace events; write to `filename` if given.

    `format="chrome"` writes the JSON *Object* envelope
    (`{"traceEvents": [...]}`) that Perfetto/chrome://tracing load
    directly; the default (legacy) writes the bare event array. Both
    carry the same merged content: task phases, driver scheduling
    phases, head-reconcile windows, and the workload flight recorder's
    cross-process spans (serve requests, replica execution, object
    pulls, collective ops, train steps) with flow arrows across
    processes."""
    from ray_tpu.util.state import list_task_events

    events = list_task_events()
    open_spans = {}   # task_id -> RUNNING event
    trace: List[dict] = []
    names = {}
    for ev in events:
        if ev["state"] == "RUNNING":
            open_spans[ev["task_id"]] = ev
            if ev["name"]:
                names[ev["task_id"]] = ev["name"]
        elif ev["state"] in ("FINISHED", "FAILED"):
            start = open_spans.pop(ev["task_id"], None)
            if start is None:
                continue
            trace.append({
                "name": names.get(ev["task_id"], "task"),
                "cat": "task",
                "ph": "X",
                "ts": start["ts"] * 1e6,
                "dur": (ev["ts"] - start["ts"]) * 1e6,
                "pid": start["node_id"] or "head",
                "tid": start["worker_id"] or "worker",
                "args": {"task_id": ev["task_id"],
                         "failed": ev["state"] == "FAILED"},
            })
    # still-running tasks: begin events so they show in the trace
    for task_id, start in open_spans.items():
        trace.append({"name": names.get(task_id, "task"), "cat": "task",
                      "ph": "B", "ts": start["ts"] * 1e6,
                      "pid": start["node_id"] or "head",
                      "tid": start["worker_id"] or "worker",
                      "args": {"task_id": task_id}})
    _sched_phase_events(trace)
    _reconcile_phase_events(trace)
    _workload_span_events(trace)
    if filename:
        payload = ({"traceEvents": trace, "displayTimeUnit": "ms"}
                   if format == "chrome" else trace)
        with open(filename, "w") as f:
            json.dump(payload, f)
    return trace
