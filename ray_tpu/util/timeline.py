"""`ray_tpu.timeline()`: Chrome-trace dump of task execution.

Parity: the `ray timeline` CLI (`python/ray/scripts/scripts.py`) which turns
profile events into a chrome://tracing JSON file. Here RUNNING→FINISHED/
FAILED transitions from the head's task-event buffer become complete ("X")
trace events, one row per worker.
"""

from __future__ import annotations

import json
from typing import List, Optional


def timeline(filename: Optional[str] = None) -> List[dict]:
    """Build Chrome trace events; write to `filename` if given."""
    from ray_tpu.util.state import list_task_events

    events = list_task_events()
    open_spans = {}   # task_id -> RUNNING event
    trace: List[dict] = []
    names = {}
    for ev in events:
        if ev["state"] == "RUNNING":
            open_spans[ev["task_id"]] = ev
            if ev["name"]:
                names[ev["task_id"]] = ev["name"]
        elif ev["state"] in ("FINISHED", "FAILED"):
            start = open_spans.pop(ev["task_id"], None)
            if start is None:
                continue
            trace.append({
                "name": names.get(ev["task_id"], "task"),
                "cat": "task",
                "ph": "X",
                "ts": start["ts"] * 1e6,
                "dur": (ev["ts"] - start["ts"]) * 1e6,
                "pid": start["node_id"] or "head",
                "tid": start["worker_id"] or "worker",
                "args": {"task_id": ev["task_id"],
                         "failed": ev["state"] == "FAILED"},
            })
    # still-running tasks: begin events so they show in the trace
    for task_id, start in open_spans.items():
        trace.append({"name": names.get(task_id, "task"), "cat": "task",
                      "ph": "B", "ts": start["ts"] * 1e6,
                      "pid": start["node_id"] or "head",
                      "tid": start["worker_id"] or "worker",
                      "args": {"task_id": task_id}})
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace
