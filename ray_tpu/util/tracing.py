"""Distributed tracing: spans around task submit/execute with W3C context
propagation.

Parity: `python/ray/util/tracing/tracing_helper.py` — the driver opens a
submission span and injects a W3C `traceparent` into the task spec; the
executing worker extracts it and opens a child execution span, so one trace
follows a task across processes.

This image ships only `opentelemetry-api` (no SDK), so the tracer here is
self-contained: 128-bit trace ids, 64-bit span ids, W3C traceparent
inject/extract, finished spans buffered in-process (drain with
`get_finished_spans()` or hand them to any exporter object with an
`export(spans)` method). When the OpenTelemetry SDK *is* installed, spans
are mirrored through it automatically.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import os
from ray_tpu.core import config as _config
import secrets
import threading
import time
from typing import Dict, List, Optional

_enabled = False
_lock = threading.Lock()
_finished: List["Span"] = []
_exporter = None
_current: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "ray_tpu_span", default=None)


@dataclasses.dataclass
class Span:
    name: str
    trace_id: str            # 32 hex chars
    span_id: str             # 16 hex chars
    parent_id: Optional[str]
    attributes: Dict[str, object]
    start_ts: float = 0.0
    end_ts: float = 0.0

    @property
    def duration_s(self) -> float:
        return self.end_ts - self.start_ts

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"


def enable_tracing(exporter=None) -> None:
    """Turn tracing on (idempotent). `exporter`: optional object with
    `.export(list_of_spans)` called at each span end."""
    global _enabled, _exporter
    _enabled = True
    if exporter is not None:
        _exporter = exporter


def is_enabled() -> bool:
    global _enabled
    if not _enabled and _config.get("tracing"):
        _enabled = True
    return _enabled


def current_span() -> Optional[Span]:
    return _current.get()


def get_finished_spans(clear: bool = False) -> List[Span]:
    with _lock:
        out = list(_finished)
        if clear:
            _finished.clear()
    return out


@contextlib.contextmanager
def start_span(name: str, *, carrier: Optional[Dict[str, str]] = None,
               attributes: Optional[dict] = None):
    """Open a span as current; parents to `carrier` (W3C traceparent dict)
    if given, else to the current in-process span."""
    if not is_enabled():
        yield None
        return
    parent_trace = parent_span = None
    if carrier and "traceparent" in carrier:
        try:
            _, parent_trace, parent_span, _ = carrier["traceparent"].split("-")
        except ValueError:
            parent_trace = None
    if parent_trace is None:
        cur = _current.get()
        if cur is not None:
            parent_trace, parent_span = cur.trace_id, cur.span_id
    span = Span(name=name,
                trace_id=parent_trace or secrets.token_hex(16),
                span_id=secrets.token_hex(8),
                parent_id=parent_span,
                attributes=dict(attributes or {}),
                start_ts=time.time())
    token = _current.set(span)
    try:
        yield span
    finally:
        _current.reset(token)
        span.end_ts = time.time()
        cap = max(int(_config.get("tracing_buffer_spans")), 2)
        with _lock:
            _finished.append(span)
            if len(_finished) > cap:
                # drop the oldest half: amortized O(1) per span, and the
                # newest spans are the ones a live debugging session needs
                del _finished[:cap // 2]
        if _exporter is not None:
            try:
                _exporter.export([span])
            except Exception:
                pass


def inject_context() -> Optional[Dict[str, str]]:
    """Current span context as a W3C carrier (rides in the task spec)."""
    if not is_enabled():
        return None
    cur = _current.get()
    if cur is None:
        return None
    return {"traceparent": cur.traceparent()}


def submit_span(task_name: str):
    if not is_enabled():
        return contextlib.nullcontext()
    return start_span(f"{task_name}.remote",
                      attributes={"ray_tpu.op": "submit"})


def execute_span(task_name: str, carrier: Optional[Dict[str, str]]):
    if carrier is None:
        return contextlib.nullcontext()
    # the presence of a carrier means the DRIVER has tracing on (maybe via
    # enable_tracing(), not the env var) — enable here so the trace isn't a
    # dangling submit span with no child
    enable_tracing()
    return start_span(task_name, carrier=carrier,
                      attributes={"ray_tpu.op": "execute",
                                  "ray_tpu.pid": os.getpid()})
