"""Distributed tracing: spans around task submit/execute with W3C context
propagation.

Parity: `python/ray/util/tracing/tracing_helper.py` — the driver opens a
submission span and injects a W3C `traceparent` into the task spec; the
executing worker extracts it and opens a child execution span, so one trace
follows a task across processes.

This image ships only `opentelemetry-api` (no SDK), so the tracer here is
self-contained: 128-bit trace ids, 64-bit span ids, W3C traceparent
inject/extract, finished spans buffered in-process (drain with
`get_finished_spans()` or hand them to any exporter object with an
`export(spans)` method). When the OpenTelemetry SDK *is* installed, spans
are mirrored through it automatically.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import os
from ray_tpu.core import config as _config
import secrets
import threading
import time
from typing import Dict, List, Optional

_enabled = False
_lock = threading.Lock()
_finished: List["Span"] = []
# spans waiting to ride the next metrics push to the head (workload
# tracing: the head accumulates every process's spans so timeline() can
# merge one cross-process trace) — bounded separately from _finished
_push_queue: List[dict] = []
_dropped_counter = None
_exporter = None
_current: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "ray_tpu_span", default=None)


@dataclasses.dataclass
class Span:
    name: str
    trace_id: str            # 32 hex chars
    span_id: str             # 16 hex chars
    parent_id: Optional[str]
    attributes: Dict[str, object]
    start_ts: float = 0.0
    end_ts: float = 0.0

    @property
    def duration_s(self) -> float:
        return self.end_ts - self.start_ts

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def to_dict(self) -> dict:
        """JSON-safe form (rides the metrics push to the head)."""
        attrs = {k: (v if isinstance(v, (str, int, float, bool)) else str(v))
                 for k, v in self.attributes.items()}
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start_ts": self.start_ts, "end_ts": self.end_ts,
                "attributes": attrs}


def enable_tracing(exporter=None) -> None:
    """Turn tracing on (idempotent). `exporter`: optional object with
    `.export(list_of_spans)` called at each span end."""
    global _enabled, _exporter
    _enabled = True
    if exporter is not None:
        _exporter = exporter


def is_enabled() -> bool:
    global _enabled
    if not _enabled and _config.get("tracing"):
        _enabled = True
    return _enabled


def current_span() -> Optional[Span]:
    return _current.get()


def is_recording() -> bool:
    """True when a span opened now would record: tracing is enabled
    process-wide, or we are inside an active trace context (a remote
    caller's context adopted per-request — the OTel sampling model:
    the root decides, children follow the parent)."""
    return is_enabled() or _current.get() is not None


def get_finished_spans(clear: bool = False) -> List[Span]:
    with _lock:
        out = list(_finished)
        if clear:
            _finished.clear()
    return out


@contextlib.contextmanager
def start_span(name: str, *, carrier: Optional[Dict[str, str]] = None,
               attributes: Optional[dict] = None):
    """Open a span as current; parents to `carrier` (W3C traceparent dict)
    if given, else to the current in-process span.

    Records when tracing is enabled process-wide, OR when a parent
    context exists (a carrier, or an in-process current span): a traced
    request's children record in every process it crosses without
    flipping any process-wide switch — per-request tracing stays
    per-request."""
    parent_trace = parent_span = None
    carrier_sampled = False
    if carrier and "traceparent" in carrier:
        # strict parse: a malformed header (LBs and APM agents inject
        # these freely) must NOT force recording, and neither must a
        # valid one whose W3C sampled flag is 00
        try:
            _, t, s, flags = carrier["traceparent"].split("-")
        except ValueError:
            t = s = flags = None
        if t and len(t) == 32 and s and len(s) == 16:
            parent_trace, parent_span = t, s
            carrier_sampled = flags != "00"
    if not (is_enabled() or _current.get() is not None or carrier_sampled):
        yield None
        return
    if parent_trace is None:
        cur = _current.get()
        if cur is not None:
            parent_trace, parent_span = cur.trace_id, cur.span_id
    span = Span(name=name,
                trace_id=parent_trace or secrets.token_hex(16),
                span_id=secrets.token_hex(8),
                parent_id=parent_span,
                attributes=dict(attributes or {}),
                start_ts=time.time())
    token = _current.set(span)
    try:
        yield span
    finally:
        _current.reset(token)
        span.end_ts = time.time()
        cap = max(int(_config.get("tracing_buffer_spans")), 2)
        dropped = 0
        with _lock:
            _finished.append(span)
            if len(_finished) > cap:
                # drop the oldest half: amortized O(1) per span, and the
                # newest spans are the ones a live debugging session needs
                del _finished[:cap // 2]
            _push_queue.append(span.to_dict())
            if len(_push_queue) > cap:
                dropped = cap // 2
                del _push_queue[:dropped]
        if dropped:
            _count_dropped(dropped)
        if _exporter is not None:
            try:
                _exporter.export([span])
            except Exception:
                pass


def _count_dropped(n: int) -> None:
    """Spans dropped before reaching the head are invisible losses unless
    counted — `trace_spans_dropped_total` makes the budget observable."""
    global _dropped_counter
    try:
        if _dropped_counter is None:
            from ray_tpu.util import metrics as _m

            _dropped_counter = _m.Counter(
                "trace_spans_dropped_total",
                "Finished spans dropped from the push buffer before the "
                "head could collect them (raise tracing_buffer_spans)")
        _dropped_counter.inc(n)
    except Exception:
        pass


def drain_push_spans(limit: int = 512) -> List[dict]:
    """Pop up to `limit` finished-span dicts for the metrics push (the
    head accumulates them for cross-process timeline export)."""
    with _lock:
        out = _push_queue[:limit]
        del _push_queue[:limit]
    return out


def requeue_push_spans(spans: List[dict]) -> None:
    """Put drained spans back after a failed push so a transient head
    outage doesn't silently hole the cross-process timeline; overflow
    (oldest first) is counted as dropped like any other loss."""
    if not spans:
        return
    cap = max(int(_config.get("tracing_buffer_spans")), 2)
    with _lock:
        _push_queue[:0] = spans
        overflow = len(_push_queue) - cap
        if overflow > 0:
            del _push_queue[:overflow]
    if overflow > 0:
        _count_dropped(overflow)


@contextlib.contextmanager
def adopt_context(carrier: Optional[Dict[str, str]]):
    """Make `carrier`'s span current WITHOUT recording a new span: code
    that runs on behalf of a remote caller (dependency fetches before the
    execute span opens, a daemon serving a pull) parents any spans it
    opens to the caller's context. A carrier's presence means the origin
    traces, so tracing is enabled here (same contract as execute_span)."""
    if not carrier or "traceparent" not in carrier:
        yield None
        return
    try:
        _, trace_id, span_id, _ = carrier["traceparent"].split("-")
    except ValueError:
        yield None
        return
    synthetic = Span(name="(remote)", trace_id=trace_id, span_id=span_id,
                     parent_id=None, attributes={})
    token = _current.set(synthetic)
    try:
        yield synthetic
    finally:
        _current.reset(token)


def inject_context() -> Optional[Dict[str, str]]:
    """Current span context as a W3C carrier (rides in the task spec).
    Keyed on the CURRENT span, not the process-wide switch: a span only
    becomes current when it recorded, so per-request traces propagate
    without enabling tracing for unrelated work."""
    cur = _current.get()
    if cur is None:
        return None
    return {"traceparent": cur.traceparent()}


def submit_span(task_name: str):
    if not is_recording():
        return contextlib.nullcontext()
    return start_span(f"{task_name}.remote",
                      attributes={"ray_tpu.op": "submit"})


def execute_span(task_name: str, carrier: Optional[Dict[str, str]]):
    if carrier is None:
        return contextlib.nullcontext()
    # the carrier's presence means the ORIGIN traces this operation;
    # start_span records on it without flipping this process's switch,
    # so one traced request doesn't turn tracing on for everything else
    return start_span(task_name, carrier=carrier,
                      attributes={"ray_tpu.op": "execute",
                                  "ray_tpu.pid": os.getpid()})


def request_span(name: str, carrier: Optional[Dict[str, str]],
                 attributes: Optional[dict] = None):
    """Root/continuation span for an ingress request (serve HTTP/gRPC
    proxies): a client-supplied W3C `traceparent` traces THIS request
    even when the cluster flag is off (the carrier clause in start_span
    — no process-wide state changes); without a carrier this opens a
    root span only when tracing is already enabled."""
    if not carrier and not is_enabled():
        return contextlib.nullcontext()
    return start_span(name, carrier=carrier, attributes=attributes)
