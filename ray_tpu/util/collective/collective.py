"""Collective API surface — parity with `ray.util.collective.collective`.

Reference: `python/ray/util/collective/collective.py` (init_collective_group
:166, create_collective_group :203, get_rank, allreduce :311, barrier :351,
reduce :364, broadcast :426, allgather :476, reducescatter :525, send/recv
:584-705). Backends here are TPU-native (see types.py): `kv` for
cross-process actor gangs (CI/CPU), `xla` for in-process device gangs.

Rendezvous: group metadata lives in the head KV store (the reference uses a
named detached Info actor, collective.py:260-265, and internal KV for gloo);
declarative creation writes actor-id→rank there and members lazily attach.
"""

from __future__ import annotations

import pickle
import threading
from typing import List, Optional

import numpy as np

from ray_tpu.util.collective.hierarchy import Topology
from ray_tpu.util.collective.kv_group import KVCollectiveGroup
from ray_tpu.util.collective.quantize import QuantizedAllreduce
from ray_tpu.util.collective.reshard import (WindowedReader, reshard,
                                             reshard_streaming, reshard_tree)
from ray_tpu.util.collective.types import Backend, ReduceOp
from ray_tpu.util.collective.xla_group import XlaCollectiveGroup

_META_NS = "collective_meta"
_groups: dict = {}
_lock = threading.Lock()


def _client():
    from ray_tpu.core.api import _global_client

    return _global_client()


def init_collective_group(world_size: int, rank: int, backend: str = "kv",
                          group_name: str = "default") -> None:
    """Imperative init: every member calls this with its own rank."""
    backend = Backend(backend)
    with _lock:
        if group_name in _groups:
            raise RuntimeError(f"group {group_name!r} already initialized")
        if backend == Backend.XLA:
            raise ValueError(
                "backend='xla' groups are in-process device gangs; build one "
                "with ray_tpu.util.collective.XlaCollectiveGroup(devices)")
        _groups[group_name] = _make_group(backend, group_name, world_size,
                                          rank)


def create_collective_group(actors: list, world_size: int, ranks: List[int],
                            backend: str = "kv",
                            group_name: str = "default") -> None:
    """Declarative init from the driver: members lazily attach on first op."""
    Backend(backend)
    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError("actors/ranks must both have world_size entries")
    mapping = {a._actor_id.hex(): r for a, r in zip(actors, ranks)}
    meta = {"world_size": world_size, "ranks": mapping, "backend": backend}
    ok = _client().kv_put(_META_NS, group_name.encode(), pickle.dumps(meta),
                          overwrite=False)
    if not ok:
        raise RuntimeError(f"collective group {group_name!r} already exists")


def _make_group(backend, group_name: str, world_size: int, rank: int):
    if backend == Backend.XLA_MULTIHOST:
        from ray_tpu.util.collective.xla_multihost import XlaMultihostGroup

        return XlaMultihostGroup(_client(), group_name, world_size, rank)
    return KVCollectiveGroup(_client(), group_name, world_size, rank)


def _lazy_attach(group_name: str) -> KVCollectiveGroup:
    blob = _client().kv_get(_META_NS, group_name.encode())
    if blob is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized; call "
            "init_collective_group or create_collective_group first")
    meta = pickle.loads(blob)
    actor_id = _client().current_actor_id
    if actor_id is None or actor_id.hex() not in meta["ranks"]:
        raise RuntimeError(
            f"this process is not a member of group {group_name!r}")
    group = _make_group(Backend(meta.get("backend", "kv")), group_name,
                        meta["world_size"], meta["ranks"][actor_id.hex()])
    _groups[group_name] = group
    return group


def _get_group(group_name: str) -> KVCollectiveGroup:
    with _lock:
        group = _groups.get(group_name)
        if group is None:
            group = _lazy_attach(group_name)
        return group


def get_group(group_name: str = "default"):
    """The live group object (attaching lazily if declared remotely) —
    for callers that need backend-level knobs the module-level wrappers
    don't expose (per-op timeouts, elastic rebuilds)."""
    return _get_group(group_name)


def rebuild_collective_group(world_size: int, rank: int, backend: str = "kv",
                             group_name: str = "default") -> None:
    """Tear down any existing local membership of `group_name` and re-init
    at a NEW world size / rank — the membership-change path for elastic
    training: after a gang shrinks or regrows, every surviving member
    calls this with its new coordinates before the next collective.

    Unlike `init_collective_group` this never raises on an existing
    group; the previous incarnation's local state is destroyed first
    (its rendezvous keys are garbage-collected by `destroy`). Callers
    that rebuild across process *restarts* should put a generation tag
    in `group_name` (e.g. "ddp:g3") so a zombie member of the fenced
    gang can never rendezvous with the new one.
    """
    backend = Backend(backend)
    # pop, destroy, and install under ONE lock hold: releasing between
    # the pop and the install lets a concurrent rebuild/lazy-attach slip
    # a group with DIFFERENT coordinates into the gap (the caller would
    # then silently rendezvous with the wrong world_size/rank). destroy()
    # runs inside the hold too so the old incarnation's key GC can't
    # race the new group's first posts.
    with _lock:
        group = _groups.pop(group_name, None)
        if group is not None:
            try:
                group.destroy()
            except Exception:
                pass
        _groups[group_name] = _make_group(backend, group_name,
                                          world_size, rank)


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def destroy_collective_group(group_name: str = "default") -> None:
    with _lock:
        group = _groups.pop(group_name, None)
    if group is not None:
        group.destroy()
    try:
        _client().kv_del(_META_NS, group_name.encode())
    except Exception:
        pass


def get_rank(group_name: str = "default") -> int:
    return _get_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _get_group(group_name).world_size


# --------------------------------------------------------------- collectives
def _tensor_info(tensor) -> tuple:
    """(nbytes, dtype) without materializing the tensor (shape/dtype
    attributes only; np coercion would force a device fetch)."""
    try:
        nbytes = int(getattr(tensor, "nbytes", 0) or 0)
        dtype = str(getattr(tensor, "dtype", "") or "unknown")
        return nbytes, dtype
    except Exception:
        return 0, "unknown"


def _op_span(op_name: str, group_name: str, tensor=None):
    """Child span for one collective op when the calling context traces
    (the span joins the consuming task's/train step's trace); a cheap
    nullcontext otherwise — the warm path pays one is_enabled() check.
    Bytes/dtype ride the span attributes so the chrome timeline shows
    comm phases with their wire cost; the same numbers feed the
    `collective_bytes_total{op,dtype,hop}` counter."""
    import contextlib

    from ray_tpu.util import tracing

    nbytes, dtype = _tensor_info(tensor)
    if nbytes:
        from ray_tpu.util.collective.hierarchy import account_collective

        account_collective(op_name, nbytes, dtype, hop="world")
    if not tracing.is_recording():
        return contextlib.nullcontext()
    return tracing.start_span(
        f"collective.{op_name}",
        attributes={"ray_tpu.op": "collective", "group": group_name,
                    "collective.op": op_name, "collective.bytes": nbytes,
                    "collective.dtype": dtype})


def allreduce(tensor, op: ReduceOp = ReduceOp.SUM,
              group_name: str = "default"):
    with _op_span("allreduce", group_name, tensor):
        return _get_group(group_name).allreduce(tensor, op)


def reduce(tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM,
           group_name: str = "default"):
    with _op_span("reduce", group_name, tensor):
        return _get_group(group_name).reduce(tensor, dst_rank, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    with _op_span("broadcast", group_name, tensor):
        return _get_group(group_name).broadcast(tensor, src_rank)


def allgather(tensor_list: Optional[list], tensor, group_name: str = "default"):
    """Reference signature: fills tensor_list with world_size tensors."""
    with _op_span("allgather", group_name, tensor):
        parts = _get_group(group_name).allgather(tensor)
    if tensor_list is not None:
        tensor_list[:] = parts
    return parts


def reducescatter(tensor, op: ReduceOp = ReduceOp.SUM,
                  group_name: str = "default"):
    with _op_span("reducescatter", group_name, tensor):
        return _get_group(group_name).reducescatter(tensor, op)


def barrier(group_name: str = "default") -> None:
    with _op_span("barrier", group_name):
        _get_group(group_name).barrier()


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    _get_group(group_name).send(tensor, dst_rank)


def recv(tensor, src_rank: int, group_name: str = "default"):
    return _get_group(group_name).recv(tensor, src_rank)


def synchronize(device_or_group=None) -> None:
    """Block until all queued device work completes (reference :708 syncs
    the CUDA stream; on TPU the analog is draining dispatched XLA work)."""
    import jax

    (jax.device_put(np.zeros(()))).block_until_ready()


__all__ = [
    "init_collective_group", "create_collective_group",
    "rebuild_collective_group", "get_group",
    "destroy_collective_group", "is_group_initialized", "get_rank",
    "get_collective_group_size", "allreduce", "reduce", "broadcast",
    "allgather", "reducescatter", "barrier", "send", "recv", "synchronize",
    "ReduceOp", "Backend", "XlaCollectiveGroup",
    "Topology", "QuantizedAllreduce", "reshard", "reshard_streaming",
    "reshard_tree", "WindowedReader",
]
