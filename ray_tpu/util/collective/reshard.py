"""Portable array resharding (the array-redistribution direction in
PAPERS.md, specialized to the two places meshes actually change shape:
checkpoint restore under a different world size, and
`adaptive_mesh_config` reshapes after elastic shrink/regrow).

Two schedules, picked by where the source data lives:

- **in-mesh** (`arr` is a jax.Array whose mesh == the destination's):
  one jitted identity with `out_shardings=dst` — XLA emits the
  memory-efficient all-to-all / collective-permute redistribution plan
  itself, never materializing the global array on any device;
- **cross-mesh / host** (numpy source, or a jax.Array on a different
  mesh — the restore-under-new-mesh case): per-destination-shard window
  assembly. Each addressable device receives ONLY its own index window
  (`device_put` of a host slice), so peak device memory is one shard,
  not one full copy per device — the memory-efficient schedule the
  array-redistribution paper describes, degenerated to the host-mediated
  case. Replicated windows are sliced once and fanned out.

`reshard` replaces the old gather-then-`device_put`-the-full-array hop in
`restore_state_sharded`; round-trips are bitwise (no dtype or value
changes, only placement).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import numpy as np


def _dst_mesh(sharding):
    return getattr(sharding, "mesh", None)


def _identity(x):
    return x


@functools.lru_cache(maxsize=256)
def _jit_identity_for(dst_sharding):
    """One jitted identity per destination sharding: a fresh
    `jax.jit(lambda ...)` per call would miss the executable cache and
    recompile the redistribution program for every leaf of a pytree."""
    import jax

    return jax.jit(_identity, out_shardings=dst_sharding)


def reshard(arr: Any, dst_sharding, *, src_sharding=None):
    """Redistribute `arr` (numpy or jax.Array) to `dst_sharding`.

    `src_sharding` is accepted for API symmetry/documentation; the actual
    source layout is read off the array itself (a jax.Array knows its
    sharding, a numpy array is host-global).
    """
    import jax

    if isinstance(arr, jax.Array) and not isinstance(arr, jax.core.Tracer):
        src_mesh = _dst_mesh(getattr(arr, "sharding", None))
        if src_mesh is not None and src_mesh == _dst_mesh(dst_sharding):
            # same mesh (addressable or multi-host global): let XLA plan
            # the redistribution (all-to-all / collective-permute inside
            # one program, no host bounce)
            return _jit_identity_for(dst_sharding)(arr)
        if not arr.is_fully_addressable:
            raise ValueError(
                "reshard across DIFFERENT meshes needs a host-stageable "
                "source, but this jax.Array spans non-addressable "
                "devices; gather it per process first (the checkpoint "
                "path does: save_sharded writes addressable chunks, "
                "load_sharded reassembles the host array)")
        arr = np.asarray(arr)  # cross-mesh: stage through host windows
    else:
        arr = np.asarray(arr)

    shape = arr.shape
    if not shape:
        return jax.device_put(arr, dst_sharding)
    imap = dst_sharding.addressable_devices_indices_map(shape)
    windows: dict = {}  # index-window key -> host slice (sliced once)
    shards = []
    for dev, idx in imap.items():
        idx = idx if idx is not None else tuple(slice(None) for _ in shape)
        key = tuple((s.start, s.stop, s.step) for s in idx)
        win = windows.get(key)
        if win is None:
            win = windows[key] = np.ascontiguousarray(arr[idx])
        shards.append(jax.device_put(win, dev))
    return jax.make_array_from_single_device_arrays(
        shape, dst_sharding, shards)


class WindowedReader:
    """Duck-typed host source for `reshard_streaming`: `.shape`/`.dtype`
    plus `.read(window)` assembling the requested global index window
    from lazily-loaded chunk blobs.

    `chunks` is [(window, key)] in global coordinates
    (window = ((start, stop), ...) per dim); `loader(key, r0, r1)` must
    return rows [r0, r1) of that chunk's LEADING dim as an ndarray —
    e.g. a seek-read of a checkpoint npz member
    (`checkpoint.open_sharded`), or a `client.get` of an object-store
    blob, which rides the node PullManager (admission + chunk-pipelined
    transfer + in-flight dedup across concurrent readers).
    """

    def __init__(self, shape, dtype, chunks, loader):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        self._chunks = [(tuple((int(a), int(b)) for a, b in win), key)
                        for win, key in chunks]
        self._loader = loader

    def read(self, window) -> np.ndarray:
        window = tuple((int(a), int(b)) for a, b in window)
        out = np.zeros([b - a for a, b in window], self.dtype)
        if not window:  # scalar leaf: any chunk IS the value
            for _, key in self._chunks:
                out[...] = np.asarray(self._loader(key, 0, 1)).reshape(())
            return out
        for cwin, key in self._chunks:
            inter = tuple((max(a, ca), min(b, cb))
                          for (a, b), (ca, cb) in zip(window, cwin))
            if any(a >= b for a, b in inter):
                continue
            r0, r1 = inter[0][0] - cwin[0][0], inter[0][1] - cwin[0][0]
            rows = np.asarray(self._loader(key, r0, r1))
            sub = rows[(slice(None),) + tuple(
                slice(a - ca, b - ca) for (a, b), (ca, _) in
                zip(inter[1:], cwin[1:]))]
            out[tuple(slice(a - wa, b - wa) for (a, b), (wa, _) in
                      zip(inter, window))] = sub
        return out


# Instrumentation for the most recent reshard_streaming call: peak bytes
# of live host chunk buffers (the budget the tests assert), chunk count,
# distinct destination windows. Module-level on purpose — the caller that
# needs it (tests, benches) runs reshards serially.
last_stream_stats: dict = {}


def reshard_streaming(src: Any, dst_sharding, *, chunk_bytes: int,
                      max_in_flight: int = 2, out_dtype=None):
    """`reshard` for leaves larger than host memory: per-destination-
    window assembly proceeds CHUNK-AT-A-TIME instead of slicing a
    materialized global array.

    `src` is an ndarray or a duck-typed reader (`.shape`/`.dtype`/
    `.read(window)` — see `WindowedReader`). Each deduplicated
    destination window is split along its leading dim into row chunks of
    at most `chunk_bytes`; a `max_in_flight`-deep prefetch pipeline
    overlaps the next chunk's host read with the current chunk's
    `device_put`, so peak host memory is ~`max_in_flight * chunk_bytes`
    (down to single-row granularity) rather than the leaf size. Chunks
    are concatenated ON DEVICE into the final shard: the result is
    bitwise-equal to `reshard` of the same data. `out_dtype` converts
    per chunk (host cost stays chunk-scale).
    """
    import threading
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    import jax
    import jax.numpy as jnp

    if chunk_bytes <= 0:
        raise ValueError("chunk_bytes must be positive")
    max_in_flight = max(1, int(max_in_flight))
    reader = src if hasattr(src, "read") else _HostReader(np.asarray(src))
    shape = tuple(reader.shape)
    dtype = np.dtype(out_dtype) if out_dtype is not None else np.dtype(
        reader.dtype)
    if not shape:
        a = np.asarray(reader.read(()), np.dtype(reader.dtype)).astype(
            dtype, copy=False)
        last_stream_stats.update(
            peak_host_bytes=a.nbytes, chunks=1, windows=1)
        return jax.device_put(a.reshape(()), dst_sharding)

    imap = dst_sharding.addressable_devices_indices_map(shape)
    windows: dict = {}  # window key -> [devices]
    for dev, idx in imap.items():
        idx = idx if idx is not None else tuple(slice(None) for _ in shape)
        key = tuple((0 if s.start is None else int(s.start),
                     dim if s.stop is None else int(s.stop))
                    for s, dim in zip(idx, shape))
        windows.setdefault(key, []).append(dev)

    tasks = []  # (devices, window-key, sub-window)
    for key, devs in windows.items():
        (w0, w1), trailing = key[0], key[1:]
        row_bytes = dtype.itemsize
        for a, b in trailing:
            row_bytes *= (b - a)
        rows_per = max(1, chunk_bytes // max(1, row_bytes))
        for r0 in range(w0, w1, rows_per):
            tasks.append((devs, key, ((r0, min(r0 + rows_per, w1)),)
                          + trailing))
        if w0 >= w1:  # degenerate empty window: one empty chunk
            tasks.append((devs, key, key))

    stats = {"peak_host_bytes": 0, "chunks": 0, "windows": len(windows)}
    live = {"bytes": 0}
    lock = threading.Lock()

    def _read(sub):
        a = np.ascontiguousarray(reader.read(sub))
        if a.dtype != dtype:
            a = a.astype(dtype)
        with lock:
            live["bytes"] += a.nbytes
            stats["peak_host_bytes"] = max(stats["peak_host_bytes"],
                                           live["bytes"])
        return a

    parts: dict = {}  # window key -> [device -> [chunk arrays]]
    with ThreadPoolExecutor(max_workers=max_in_flight) as pool:
        q: deque = deque()
        ti = 0

        def _fill():
            nonlocal ti
            while len(q) < max_in_flight and ti < len(tasks):
                devs, key, sub = tasks[ti]
                ti += 1
                q.append((devs, key, pool.submit(_read, sub)))

        _fill()
        while q:
            devs, key, fut = q.popleft()
            a = fut.result()
            puts = [jax.device_put(a, d) for d in devs]
            jax.block_until_ready(puts)  # host buffer free AFTER transfer
            for d, p in zip(devs, puts):
                parts.setdefault(key, {}).setdefault(d, []).append(p)
            with lock:
                live["bytes"] -= a.nbytes
            del a
            stats["chunks"] += 1
            _fill()

    shards = []
    for dev, idx in imap.items():
        idx = idx if idx is not None else tuple(slice(None) for _ in shape)
        key = tuple((0 if s.start is None else int(s.start),
                     dim if s.stop is None else int(s.stop))
                    for s, dim in zip(idx, shape))
        ps = parts[key][dev]
        shards.append(ps[0] if len(ps) == 1 else jnp.concatenate(ps, axis=0))
    last_stream_stats.clear()
    last_stream_stats.update(stats)
    return jax.make_array_from_single_device_arrays(
        shape, dst_sharding, shards)


class _HostReader:
    """`WindowedReader` facade over an in-memory ndarray."""

    def __init__(self, arr: np.ndarray):
        self._arr = arr
        self.shape = arr.shape
        self.dtype = arr.dtype

    def read(self, window) -> np.ndarray:
        return self._arr[tuple(slice(a, b) for a, b in window)]


def reshard_tree(tree: Any, dst_shardings: Any, *,
                 src_shardings: Optional[Any] = None):
    """`reshard` over a pytree; `dst_shardings` must match `tree`'s
    structure (extra: a single sharding broadcasts over all leaves)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    try:
        dst_leaves = jax.tree_util.tree_flatten(
            dst_shardings, is_leaf=lambda x: hasattr(x, "device_set"))[0]
        if len(dst_leaves) == 1 and len(leaves) > 1:
            dst_leaves = dst_leaves * len(leaves)
    except Exception:
        dst_leaves = [dst_shardings] * len(leaves)
    out = [reshard(l, s) for l, s in zip(leaves, dst_leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


__all__ = ["reshard", "reshard_streaming", "reshard_tree",
           "WindowedReader", "last_stream_stats"]
