"""Portable array resharding (the array-redistribution direction in
PAPERS.md, specialized to the two places meshes actually change shape:
checkpoint restore under a different world size, and
`adaptive_mesh_config` reshapes after elastic shrink/regrow).

Two schedules, picked by where the source data lives:

- **in-mesh** (`arr` is a jax.Array whose mesh == the destination's):
  one jitted identity with `out_shardings=dst` — XLA emits the
  memory-efficient all-to-all / collective-permute redistribution plan
  itself, never materializing the global array on any device;
- **cross-mesh / host** (numpy source, or a jax.Array on a different
  mesh — the restore-under-new-mesh case): per-destination-shard window
  assembly. Each addressable device receives ONLY its own index window
  (`device_put` of a host slice), so peak device memory is one shard,
  not one full copy per device — the memory-efficient schedule the
  array-redistribution paper describes, degenerated to the host-mediated
  case. Replicated windows are sliced once and fanned out.

`reshard` replaces the old gather-then-`device_put`-the-full-array hop in
`restore_state_sharded`; round-trips are bitwise (no dtype or value
changes, only placement).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import numpy as np


def _dst_mesh(sharding):
    return getattr(sharding, "mesh", None)


def _identity(x):
    return x


@functools.lru_cache(maxsize=256)
def _jit_identity_for(dst_sharding):
    """One jitted identity per destination sharding: a fresh
    `jax.jit(lambda ...)` per call would miss the executable cache and
    recompile the redistribution program for every leaf of a pytree."""
    import jax

    return jax.jit(_identity, out_shardings=dst_sharding)


def reshard(arr: Any, dst_sharding, *, src_sharding=None):
    """Redistribute `arr` (numpy or jax.Array) to `dst_sharding`.

    `src_sharding` is accepted for API symmetry/documentation; the actual
    source layout is read off the array itself (a jax.Array knows its
    sharding, a numpy array is host-global).
    """
    import jax

    if isinstance(arr, jax.Array) and not isinstance(arr, jax.core.Tracer):
        src_mesh = _dst_mesh(getattr(arr, "sharding", None))
        if src_mesh is not None and src_mesh == _dst_mesh(dst_sharding):
            # same mesh (addressable or multi-host global): let XLA plan
            # the redistribution (all-to-all / collective-permute inside
            # one program, no host bounce)
            return _jit_identity_for(dst_sharding)(arr)
        if not arr.is_fully_addressable:
            raise ValueError(
                "reshard across DIFFERENT meshes needs a host-stageable "
                "source, but this jax.Array spans non-addressable "
                "devices; gather it per process first (the checkpoint "
                "path does: save_sharded writes addressable chunks, "
                "load_sharded reassembles the host array)")
        arr = np.asarray(arr)  # cross-mesh: stage through host windows
    else:
        arr = np.asarray(arr)

    shape = arr.shape
    if not shape:
        return jax.device_put(arr, dst_sharding)
    imap = dst_sharding.addressable_devices_indices_map(shape)
    windows: dict = {}  # index-window key -> host slice (sliced once)
    shards = []
    for dev, idx in imap.items():
        idx = idx if idx is not None else tuple(slice(None) for _ in shape)
        key = tuple((s.start, s.stop, s.step) for s in idx)
        win = windows.get(key)
        if win is None:
            win = windows[key] = np.ascontiguousarray(arr[idx])
        shards.append(jax.device_put(win, dev))
    return jax.make_array_from_single_device_arrays(
        shape, dst_sharding, shards)


def reshard_tree(tree: Any, dst_shardings: Any, *,
                 src_shardings: Optional[Any] = None):
    """`reshard` over a pytree; `dst_shardings` must match `tree`'s
    structure (extra: a single sharding broadcasts over all leaves)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    try:
        dst_leaves = jax.tree_util.tree_flatten(
            dst_shardings, is_leaf=lambda x: hasattr(x, "device_set"))[0]
        if len(dst_leaves) == 1 and len(leaves) > 1:
            dst_leaves = dst_leaves * len(leaves)
    except Exception:
        dst_leaves = [dst_shardings] * len(leaves)
    out = [reshard(l, s) for l, s in zip(leaves, dst_leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


__all__ = ["reshard", "reshard_tree"]
