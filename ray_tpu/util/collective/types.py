"""Collective types: ReduceOp, backend registry, group options.

API parity with the reference's `python/ray/util/collective/types.py`
(ReduceOp enum, backend validation) re-expressed for the TPU stack: the
canonical backends are `xla` (in-process device collectives over a
`jax.sharding.Mesh`, the ICI data plane) and `kv` (cross-process CPU
collectives rendezvoused through the head's KV store — the CI/correctness
backend filling the role of the reference's gloo path).
"""

from __future__ import annotations

import enum
from typing import Union


class ReduceOp(enum.Enum):
    """SUM/MAX/MIN lower to native XLA primitives (psum/pmax/pmin).

    PRODUCT has no XLA primitive and lowers as all-gather-then-multiply
    on the device backends: memory bound is min(32 MiB gather cap,
    world x leaf bytes) of intermediate per chunk — the gather runs
    chunked (`hierarchy.gathered_reduce`) so a large leaf never
    materializes a full [world, ...] buffer at once."""

    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


class Backend:
    """Backend name validation (reference: types.py Backend class)."""

    XLA = "xla"      # in-process jax mesh collectives (ICI/DCN data plane)
    XLA_MULTIHOST = "xla-multihost"  # cross-process jax.distributed gang
    KV = "kv"        # cross-process via head KV + shm object store (CPU/CI)
    NCCL = "nccl"    # unavailable on TPU — rejected with guidance
    GLOO = "gloo"    # alias for KV (drop-in for reference code)
    MPI = "mpi"      # rejected, like the reference (collective.py:93-94)

    def __new__(cls, name: Union[str, "Backend"] = "xla"):
        backend = str(name).lower()
        if backend in ("xla", "ici", "tpu"):
            return Backend.XLA
        if backend in ("xla-multihost", "xla_multihost", "xmh", "multihost"):
            return Backend.XLA_MULTIHOST
        if backend in ("kv", "gloo", "torch_gloo", "cpu"):
            return Backend.KV
        if backend == "nccl":
            raise ValueError(
                "NCCL is not available on TPU; use backend='xla' (ICI "
                "collectives) or 'kv' (cross-process CPU collectives)")
        if backend == "mpi":
            raise ValueError("MPI is not supported")
        raise ValueError(f"unknown collective backend: {name!r}")


ALL_REDUCE_OPS = tuple(ReduceOp)
