"""Cross-process collective group over the head KV store + shm object store.

This is the CPU/CI backend of the collective layer — the role the reference's
pygloo group plays (`python/ray/util/collective/collective_group/
gloo_collective_group.py:185`, rendezvous via Ray internal KV,
`collective.py:101`). Data plane: small payloads ride the KV store directly;
large payloads go through the shared-memory object store and only the ref id
rides KV, so an allreduce of an N-byte tensor moves N bytes through shm per
rank pair, not through pickle frames.

Correctness model: every collective in a group is assigned a monotonically
increasing sequence number per rank (program order). Rank r posts its
contribution under (group, seq, rank) and polls for peers. A rank reaching
seq n proves it finished reading seq n-1, so each rank garbage-collects its
own key for seq n-2 when issuing seq n — the store stays O(world_size) keys
per group.
"""

from __future__ import annotations

import pickle
import time
from typing import List, Optional

import numpy as np

from ray_tpu.util.collective.types import ReduceOp

_KV_NS = "collective"
_INLINE_LIMIT = 256 * 1024
_POLL_S = 0.002


def _reduce(op: ReduceOp, arrays: List[np.ndarray]) -> np.ndarray:
    out = arrays[0].copy()
    for a in arrays[1:]:
        if op is ReduceOp.SUM:
            out += a
        elif op is ReduceOp.PRODUCT:
            out *= a
        elif op is ReduceOp.MIN:
            np.minimum(out, a, out=out)
        elif op is ReduceOp.MAX:
            np.maximum(out, a, out=out)
    return out


def _to_numpy(tensor) -> np.ndarray:
    if isinstance(tensor, np.ndarray):
        return tensor
    # jax.Array / torch.Tensor / lists all coerce via the buffer protocol
    return np.asarray(tensor)


def _write_back(tensor, value: np.ndarray):
    """In-place update when the tensor supports it; the reference's
    collectives mutate their input tensors (`collective.py:778-791`
    copies results back into torch tensors), so a torch caller porting
    code must see its tensor updated — silently returning a copy would
    leave it unchanged. jax.Arrays are immutable by design; callers get
    the returned value (documented divergence)."""
    if isinstance(tensor, np.ndarray):
        tensor[...] = value
        return tensor
    if type(tensor).__module__.startswith("torch"):
        import torch

        with torch.no_grad():
            tensor.copy_(torch.from_numpy(np.ascontiguousarray(value)))
        return tensor
    return value


class KVCollectiveGroup:
    backend_name = "kv"

    def __init__(self, client, group_name: str, world_size: int, rank: int):
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} out of range for world {world_size}")
        self._client = client
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._seq = 0
        self._p2p_seq: dict = {}  # (src, dst) -> seq
        self._owned_refs: dict = {}  # seq -> ObjectRef kept alive until gc

    # ------------------------------------------------------------- transport
    def _key(self, seq: int, rank: int, tag: str = "c") -> bytes:
        return f"{self.group_name}:{tag}:{seq}:{rank}".encode()

    def _post(self, seq: int, payload: np.ndarray, tag: str = "c",
              rank: Optional[int] = None):
        rank = self.rank if rank is None else rank
        blob = pickle.dumps(payload, protocol=5)
        if len(blob) <= _INLINE_LIMIT:
            value = b"I" + blob
        else:
            ref = self._client.put(payload)
            self._owned_refs[(tag, seq)] = ref
            value = b"R" + ref.id.binary()
        self._client.kv_put(_KV_NS, self._key(seq, rank, tag), value)

    def _fetch(self, seq: int, rank: int, tag: str = "c",
               timeout: Optional[float] = None) -> np.ndarray:
        from ray_tpu.core.object_ref import ObjectRef
        from ray_tpu.core.ids import ObjectID

        deadline = None if timeout is None else time.monotonic() + timeout
        key = self._key(seq, rank, tag)
        while True:
            value = self._client.kv_get(_KV_NS, key)
            if value is not None:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective {self.group_name} seq {seq}: rank {rank} "
                    f"did not arrive within {timeout}s")
            time.sleep(_POLL_S)
        if value[:1] == b"I":
            return pickle.loads(value[1:])
        return self._client.get([ObjectRef(ObjectID(value[1:]))])[0]

    def _gc(self, seq: int, tag: str = "c"):
        if seq >= 0:
            self._client.kv_del(_KV_NS, self._key(seq, self.rank, tag))
            ref = self._owned_refs.pop((tag, seq), None)
            if ref is not None:
                try:
                    self._client.free([ref])
                except Exception:
                    pass

    def _next_seq(self) -> int:
        seq = self._seq
        self._seq += 1
        self._gc(seq - 2)
        return seq

    def _gather_all(self, tensor, timeout=None) -> List[np.ndarray]:
        seq = self._next_seq()
        self._post(seq, _to_numpy(tensor))
        return [self._fetch(seq, r, timeout=timeout) if r != self.rank
                else _to_numpy(tensor) for r in range(self.world_size)]

    # ------------------------------------------------------------ collectives
    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM, timeout=None):
        return _write_back(tensor, _reduce(op, self._gather_all(tensor, timeout)))

    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM,
               timeout=None):
        parts = self._gather_all(tensor, timeout)
        if self.rank == dst_rank:
            return _write_back(tensor, _reduce(op, parts))
        return tensor

    def broadcast(self, tensor, src_rank: int = 0, timeout=None):
        seq = self._next_seq()
        if self.rank == src_rank:
            self._post(seq, _to_numpy(tensor))
            return tensor
        return _write_back(tensor, self._fetch(seq, src_rank, timeout=timeout))

    def allgather(self, tensor, timeout=None) -> List[np.ndarray]:
        return self._gather_all(tensor, timeout)

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM, timeout=None):
        """Input shape [world, ...]; returns this rank's reduced slice."""
        arr = _to_numpy(tensor)
        if arr.shape[0] != self.world_size:
            raise ValueError(
                f"reducescatter input leading dim {arr.shape[0]} != world "
                f"{self.world_size}")
        parts = self._gather_all(arr, timeout)
        return _reduce(op, [p[self.rank] for p in parts])

    def barrier(self, timeout=None):
        self._gather_all(np.zeros((), np.int8), timeout)

    # ------------------------------------------------------------------- p2p
    def send(self, tensor, dst_rank: int, timeout=None):
        key = (self.rank, dst_rank)
        seq = self._p2p_seq.get(key, 0)
        self._p2p_seq[key] = seq + 1
        tag = f"p{self.rank}-{dst_rank}"
        self._gc(seq - 2, tag)
        self._post(seq, _to_numpy(tensor), tag=tag)

    def recv(self, tensor, src_rank: int, timeout=None):
        key = (src_rank, self.rank)
        seq = self._p2p_seq.get(key, 0)
        self._p2p_seq[key] = seq + 1
        tag = f"p{src_rank}-{self.rank}"
        value = self._fetch(seq, src_rank, tag=tag, timeout=timeout)
        return _write_back(tensor, value)

    def destroy(self):
        for seq in range(max(0, self._seq - 2), self._seq):
            self._gc(seq)
