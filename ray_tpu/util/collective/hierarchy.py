"""Two-level (hierarchical) collectives over a hosts x local-devices topology.

TPU pods are bandwidth-asymmetric: intra-host ICI moves an order of
magnitude more bytes/s than inter-host DCN ("Collective Communication for
100k+ GPUs", arxiv 2510.20171, makes the same observation for
NVLink vs IB). A flat world allreduce pays the slow fabric the full
payload S per device; the two-level schedule pays it S/L (L = local
devices per host):

    reduce-scatter over the INTRA axis       (fast fabric, S bytes)
    allreduce of the scattered shard
        over the INTER axis                  (slow fabric, S/L bytes)
    all-gather over the INTRA axis           (fast fabric, S bytes)

Everything here is expressed as `shard_map` program bodies over a 2D mesh
(`Topology.inter_axis` x `Topology.intra_axis`), so the data plane stays
XLA collectives and the lowering is assertable: the compiled HLO must
contain a reduce-scatter plus an all-reduce whose replica groups span
ONLY the inter axis — never a flat world all-reduce (tested the same way
as `xla_multihost._rs_program`).

The inter hop optionally runs quantized (`QuantizedAllreduce`): intra
stays full precision, only the slow fabric carries int8/fp8.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.util.collective.types import ReduceOp

# ------------------------------------------------------------------ metrics
_metrics = None


def _get_metrics():
    global _metrics
    if _metrics is None:
        from ray_tpu.util import metrics as m

        _metrics = {
            "bytes": m.Counter(
                "collective_bytes_total",
                "Bytes moved by collective ops, by op/wire dtype/hop "
                "(hop: world=flat, intra=fast fabric, inter=slow fabric)",
                tag_keys=("op", "dtype", "hop")),
            "saved": m.Counter(
                "collective_quant_bytes_saved_total",
                "Wire bytes saved by quantizing the inter hop "
                "(full-precision bytes minus quantized payload+scales)"),
        }
    return _metrics


def account_collective(op: str, nbytes: int, dtype: str,
                       hop: str = "world") -> None:
    """Record wire bytes for one collective call. Host-side accounting:
    callers invoke this per launch (never from inside a traced program,
    where it would count once per compile)."""
    if nbytes <= 0:
        return
    _get_metrics()["bytes"].inc(
        float(nbytes), tags={"op": op, "dtype": dtype, "hop": hop})


def account_quant_saving(saved_bytes: int) -> None:
    if saved_bytes > 0:
        _get_metrics()["saved"].inc(float(saved_bytes))


def ring_perm(world: int) -> List[tuple]:
    """The canonical one-step ring permutation [(i, i+1 mod w)] shared by
    every ring consumer (ring attention K/V rotation, pipeline stage
    hand-off, the quantized inter ring)."""
    return [(i, (i + 1) % world) for i in range(world)]


# ----------------------------------------------------------------- topology
@dataclasses.dataclass(frozen=True)
class Topology:
    """Hosts x local-devices shape of a collective gang.

    `inter` is the slow-fabric degree (hosts / DCN), `intra` the
    fast-fabric degree (chips per host / ICI). `intra == 1` degenerates
    to a flat allreduce over `inter` members.
    """

    inter: int
    intra: int = 1
    inter_axis: str = "inter"
    intra_axis: str = "intra"

    def __post_init__(self):
        if self.inter < 1 or self.intra < 1:
            raise ValueError(f"bad topology {self.inter}x{self.intra}")

    @property
    def world(self) -> int:
        return self.inter * self.intra

    def shard_index(self, inter_pos: int, intra_pos: int) -> int:
        """Global shard slot the two-level reduce-scatter leaves on device
        (inter_pos, intra_pos). The bandwidth-optimal schedule scatters
        the INTRA axis first (full payload on the fast fabric) and the
        inter axis second (1/intra of it on the slow fabric), so shards
        land fast-axis-major: slot = intra_pos·inter + inter_pos — a
        fixed permutation of flat rank order, inverted exactly by
        `hier_all_gather_program` (gather inter first, then intra)."""
        return intra_pos * self.inter + inter_pos

    def mesh(self, devices: Sequence[Any]):
        """2D mesh over `devices` (row-major hosts x local)."""
        from jax.sharding import Mesh

        if len(devices) != self.world:
            raise ValueError(
                f"{len(devices)} devices != topology world {self.world}")
        return Mesh(np.asarray(devices).reshape(self.inter, self.intra),
                    (self.inter_axis, self.intra_axis))


def infer_topology(members: List[dict], world_size: int,
                   override: Optional[Topology] = None) -> Topology:
    """Topology from xla-multihost membership records (`_publish_membership`
    rows carry `host` + `local_devices`), or the explicit override.

    Members on the same `host` form an intra group; the hierarchy only
    engages when every host holds the same member count (asymmetric
    gangs fall back to flat, which is always correct)."""
    if override is not None:
        return override
    hosts: Dict[str, int] = {}
    for rec in members:
        hosts[str(rec.get("host", rec.get("rank")))] = (
            hosts.get(str(rec.get("host", rec.get("rank"))), 0) + 1)
    if hosts:
        counts = set(hosts.values())
        if len(counts) == 1:
            per = counts.pop()
            if per > 1 and len(hosts) * per == world_size:
                return Topology(inter=len(hosts), intra=per)
    return Topology(inter=world_size, intra=1)


# ------------------------------------------------------- fused program bodies
def _inner_reduce(op: ReduceOp, axis_name: str):
    from jax import lax

    if op is ReduceOp.SUM:
        return lambda a: lax.psum(a, axis_name)
    if op is ReduceOp.MAX:
        return lambda a: lax.pmax(a, axis_name)
    if op is ReduceOp.MIN:
        return lambda a: lax.pmin(a, axis_name)
    return lambda a: gathered_reduce(
        a, axis_name, lambda g: g.prod(axis=0))


def gathered_reduce(x, axis_name: str, reducer,
                    cap_bytes: int = 32 * (1 << 20)):
    """All-gather-then-reduce for ops XLA has no scatter/reduce primitive
    for (PRODUCT), WITHOUT materializing an unbounded [world, ...]
    intermediate: the flat input is processed in chunks so each gathered
    buffer stays under `cap_bytes` (memory bound: cap + one chunk's
    output; a naive gather peaks at world x leaf bytes, which OOMs on
    large leaves). `reducer` folds a [world, n] block to [n]."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    world = lax.psum(1, axis_name)
    if isinstance(world, jax.core.Tracer):  # pragma: no cover - mesh known
        raise ValueError("gathered_reduce requires a concrete mesh axis")
    world = int(world)
    n = int(np.prod(x.shape)) if x.shape else 1
    total = world * n * x.dtype.itemsize
    if total <= cap_bytes:
        return reducer(lax.all_gather(x, axis_name)).reshape(x.shape)
    flat = x.reshape(-1)
    per = max(1, cap_bytes // (world * x.dtype.itemsize))
    parts = []
    for s in range(0, n, per):  # static python loop: shapes are known
        g = lax.all_gather(lax.dynamic_slice_in_dim(
            flat, s, min(per, n - s)), axis_name)
        parts.append(reducer(g))
    return jnp.concatenate(parts).reshape(x.shape)


def hier_allreduce_program(topo: Topology, op: ReduceOp = ReduceOp.SUM,
                           quantize=None):
    """Body for shard_map over a `topo.mesh(...)` 2D mesh: input block
    [1, n] per device (n % intra == 0), output the fully-reduced [1, n].

    SUM lowers to reduce-scatter(intra) + allreduce(inter) + all-gather
    (intra); with `quantize` the inter hop becomes the quantized
    all-gather exchange (wire dtype int8/fp8 in the HLO). Non-sum ops
    reduce-then-slice on the intra axis (no scatter primitive), keeping
    the inter hop shard-sized all the same."""
    from jax import lax

    intra, inter = topo.intra_axis, topo.inter_axis
    inner = _inner_reduce(op, inter)

    def fn(a):
        v = a[0]
        if topo.intra > 1:
            if op is ReduceOp.SUM:
                s = lax.psum_scatter(v, intra, scatter_dimension=0,
                                     tiled=True)
            else:
                full = _inner_reduce(op, intra)(v)
                idx = lax.axis_index(intra)
                per = v.shape[0] // topo.intra
                s = lax.dynamic_slice_in_dim(full, idx * per, per)
        else:
            s = v
        if topo.inter > 1:
            if quantize is not None and op is ReduceOp.SUM:
                s = quantize.inter_allreduce(s, inter)
            else:
                s = inner(s)
        if topo.intra > 1:
            s = lax.all_gather(s, intra, tiled=True)
        return s[None]

    return fn


def hier_allreduce_ef_program(topo: Topology, quantize):
    """Error-feedback fused body: (block, residual_shard) ->
    (reduced block, new residual_shard). The residual lives at shard
    granularity (it is the quantization error of OUR scattered shard)."""
    from jax import lax

    intra, inter = topo.intra_axis, topo.inter_axis

    def fn(a, r):
        v = a[0]
        s = (lax.psum_scatter(v, intra, scatter_dimension=0, tiled=True)
             if topo.intra > 1 else v)
        out, new_r = quantize.inter_allreduce_ef(s, r[0], inter)
        if topo.intra > 1:
            out = lax.all_gather(out, intra, tiled=True)
        return out[None], new_r[None]

    return fn


def hier_grad_sync_program(topo: Topology, quantize=None,
                           error_feedback: bool = False):
    """Two-level gradient-sync body for use INSIDE a larger manual region
    (the fused train step): unlike `hier_allreduce_program` the input is
    this device's flat f32 vector [n] without the leading block dim
    (n % world == 0; quantized inter hop additionally needs
    n/intra % chunk == 0 — pad with `quantize.padded_size`), and the EF
    residual / stochastic-rounding key thread through as arguments so the
    train step can carry them as step-fn state.

    Returned fn:
        fn(v, key=None)            -> summed v            (no EF)
        fn(v, residual, key=None)  -> (summed, new_resid) (EF; residual
                                      at shard granularity [n/intra])
    The sum is NOT averaged; divide by `topo.world` at the call site.
    """
    from jax import lax

    if error_feedback and quantize is None:
        raise ValueError("error_feedback requires a quantize config")
    intra, inter = topo.intra_axis, topo.inter_axis

    def fn(v, residual=None, key=None):
        s = (lax.psum_scatter(v, intra, scatter_dimension=0, tiled=True)
             if topo.intra > 1 else v)
        new_r = None
        if topo.inter > 1:
            if quantize is not None:
                if error_feedback:
                    s, new_r = quantize.inter_allreduce_ef(
                        s, residual, inter, key=key)
                else:
                    s = quantize.inter_allreduce(s, inter, key=key)
            else:
                s = lax.psum(s, inter)
        elif error_feedback:
            new_r = residual * 0  # no inter hop => nothing was quantized
        if topo.intra > 1:
            s = lax.all_gather(s, intra, tiled=True)
        return (s, new_r) if error_feedback else s

    return fn


def hier_phase_programs(topo: Topology, quantize=None) -> Dict[str, Any]:
    """`hier_grad_sync_program` split at its phase boundaries: a dict of
    per-device flat-vector bodies {"rs", "ar", "ag"} — reduce-scatter
    over the intra (fast) axis, allreduce (optionally quantized) over
    the inter (slow) axis on the scattered shard, all-gather back.

    This is the diagnostics-window variant behind
    `spmd.compile_train(phase_timing=True)`: each phase runs as its OWN
    XLA program so host-side `block_until_ready` timing attributes step
    time to the fabric that actually spent it (RS/AG = intra ICI,
    AR = inter DCN). The single-program fusion the production step
    relies on is deliberately traded for that visibility — never run
    this as the steady-state step.

    Identity phases (degenerate axes) stay callable so the timed step's
    phase loop needs no special cases; they time at ~dispatch cost.
    """
    from jax import lax

    intra, inter = topo.intra_axis, topo.inter_axis

    def rs(v):
        return (lax.psum_scatter(v, intra, scatter_dimension=0, tiled=True)
                if topo.intra > 1 else v)

    def ar(s):
        if topo.inter > 1:
            return (quantize.inter_allreduce(s, inter)
                    if quantize is not None else lax.psum(s, inter))
        return s

    def ag(s):
        return (lax.all_gather(s, intra, tiled=True)
                if topo.intra > 1 else s)

    return {"rs": rs, "ar": ar, "ag": ag}


def hier_reduce_scatter_program(topo: Topology, op: ReduceOp = ReduceOp.SUM):
    """Two-level reduce-scatter body: input [1, n] per device; output this
    device's fully-reduced shard [1, n/world]. The inter hop moves only
    the intra-scattered shard (S/intra), then scatters it again across
    inter — inter bytes drop from N·S to S per device. Shard assignment
    is `Topology.shard_index` (fast-axis-major), NOT flat rank order —
    the price of scattering the fast axis first."""
    from jax import lax

    def fn(a):
        v = a[0]
        if topo.intra > 1:
            if op is ReduceOp.SUM:
                s = lax.psum_scatter(v, topo.intra_axis,
                                     scatter_dimension=0, tiled=True)
            else:
                full = _inner_reduce(op, topo.intra_axis)(v)
                idx = lax.axis_index(topo.intra_axis)
                per = v.shape[0] // topo.intra
                s = lax.dynamic_slice_in_dim(full, idx * per, per)
        else:
            s = v
        if topo.inter > 1:
            if op is ReduceOp.SUM:
                s = lax.psum_scatter(s, topo.inter_axis,
                                     scatter_dimension=0, tiled=True)
            else:
                full = _inner_reduce(op, topo.inter_axis)(s)
                idx = lax.axis_index(topo.inter_axis)
                per = s.shape[0] // topo.inter
                s = lax.dynamic_slice_in_dim(full, idx * per, per)
        return s[None]

    return fn


def hier_all_gather_program(topo: Topology):
    """Two-level all-gather body (inverse of the reduce-scatter): input
    this device's shard [1, n/world], output the full [1, n]. Gather over
    inter first (shard-sized on the slow fabric), then intra."""
    from jax import lax

    def fn(a):
        v = a[0]
        if topo.inter > 1:
            v = lax.all_gather(v, topo.inter_axis, tiled=True)
        if topo.intra > 1:
            v = lax.all_gather(v, topo.intra_axis, tiled=True)
        return v[None]

    return fn


def device_rows_by_process(devices: Sequence[Any]) -> List[List[Any]]:
    """Group a jax device list into per-process rows (sorted by process
    index, then device id) — the hosts x local layout `Topology.mesh`
    wants on a multi-host cluster."""
    rows: Dict[int, List[Any]] = {}
    for d in devices:
        rows.setdefault(int(d.process_index), []).append(d)
    return [sorted(rows[i], key=lambda d: d.id) for i in sorted(rows)]


__all__ = [
    "Topology", "infer_topology", "hier_allreduce_program",
    "hier_allreduce_ef_program", "hier_grad_sync_program",
    "hier_phase_programs", "hier_reduce_scatter_program",
    "hier_all_gather_program", "gathered_reduce", "device_rows_by_process",
    "account_collective", "account_quant_saving", "ring_perm",
]
