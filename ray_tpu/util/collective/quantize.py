"""Quantized allreduce building blocks (EQuARX direction, arxiv 2506.17615).

Gradient allreduce traffic tolerates aggressive compression when the
compression error is fed back into the next step (error-feedback SGD), so
the inter-host (DCN) hop of a hierarchical allreduce can run at int8/fp8
wire width while the intra-host (ICI) hops stay full precision. The
blocks here are pure `jnp`/`lax` code usable INSIDE shard_map programs:

- per-chunk absmax scales: the tensor is viewed as [n_chunks, chunk] and
  each chunk gets its own scale, so one outlier only degrades its own
  chunk (EQuARX's block-scaling observation);
- gather-based exchange: each member quantizes its OWN contribution, the
  wire moves the quantized blocks (all-gather or a ppermute ring), and
  every member dequantizes and accumulates in f32 in SOURCE-RANK order —
  sums are exact in f32 and bit-identical on every member, which a
  quantized psum (int8 accumulation, order-dependent) could not give;
- error feedback: the residual `x + r - dequant(quant(x + r))` is
  returned alongside the result and carried by the caller into the next
  call, so quantization error accumulates into later steps instead of
  being lost (determinism: same inputs + same residual state => same
  bytes, chaos-drill-verified).

Wire cost per member on the inter axis: (world-1) · S_q — the exchange
is all-gather-shaped, shipping the full packed contribution on every hop
(S_q = S/4 for int8 + ~S/chunk f32 scales) so the f32 accumulation stays
exact and rank-order-deterministic (a quantized reduce-scatter would sum
in int8: overflow + order-dependent). Against an fp32 allreduce's
2(world-1)/world · S that is a 4x saving at world=2, break-even at
world=8: the gather exchange targets SMALL inter degrees (the
hierarchical path's host axis after intra reduction). A quantized
RS+AG schedule for large host counts is a listed follow-on.
`_account_hier` (xla_multihost.py) uses the same (world-1)·wire_bytes
formula.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

# wire dtype -> (jnp dtype name, max representable magnitude used as the
# scale denominator). int8 stays symmetric at 127 so -128 never appears
# (its negation overflows); fp8 e4m3 saturates at 448.
_WIRE = {
    "int8": ("int8", 127.0),
    "float8_e4m3fn": ("float8_e4m3fn", 448.0),
}


@dataclasses.dataclass(frozen=True)
class QuantizedAllreduce:
    """Opt-in config for quantizing the inter hop of an allreduce.

    dtype: wire dtype ("int8" or "float8_e4m3fn");
    chunk: elements per scale block;
    error_feedback: carry the per-member compression residual into the
    next call (the caller owns the residual buffer between calls).
    """

    dtype: str = "int8"
    chunk: int = 4096
    error_feedback: bool = True
    # Stochastic rounding of the wire quantization: round to the two
    # nearest grid points with probability proportional to proximity, so
    # E[dequant(quant(x))] == x per element — sub-quantum gradient
    # components survive in expectation instead of rounding to zero
    # every step. Engaged only when the caller supplies a PRNG `key`
    # (the fused train step derives one from the step counter + member
    # rank); without a key the deterministic round-to-nearest runs, so
    # replay/chaos determinism contracts hold unchanged.
    stochastic_rounding: bool = False

    def __post_init__(self):
        if self.dtype not in _WIRE:
            raise ValueError(
                f"unsupported wire dtype {self.dtype!r}; pick one of "
                f"{sorted(_WIRE)}")
        if self.chunk <= 0:
            raise ValueError("chunk must be positive")
        if self.stochastic_rounding and self.dtype != "int8":
            raise ValueError(
                "stochastic_rounding rounds on the uniform int8 grid; the "
                "fp8 grid is non-uniform (per-exponent quantum) and has no "
                "unbiased dither here — use dtype='int8' or disable it")

    # ------------------------------------------------------------ properties
    @property
    def wire_dtype(self):
        return jnp.dtype(_WIRE[self.dtype][0])

    @property
    def qmax(self) -> float:
        return _WIRE[self.dtype][1]

    def key(self) -> tuple:
        return (self.dtype, self.chunk, self.error_feedback,
                self.stochastic_rounding)

    def padded_size(self, n: int) -> int:
        """Smallest multiple of `chunk` holding n elements."""
        return ((n + self.chunk - 1) // self.chunk) * self.chunk

    def wire_bytes(self, n: int) -> int:
        """Wire bytes for one member's padded contribution (payload +
        scales)."""
        np_ = self.padded_size(n)
        return np_ * self.wire_dtype.itemsize + (np_ // self.chunk) * 4

    # ------------------------------------------------------- in-program math
    def quantize(self, x, key=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Flat f32 [n] (n % chunk == 0) -> (q [nc, chunk], scales [nc, 1]).

        With `stochastic_rounding` set AND a PRNG `key` given, int8
        rounding is `floor(y + u)` for u ~ U[0,1) — unbiased per element
        (P(ceil) equals the fractional part). Each member must fold its
        own rank into the key: the dither must differ across members or
        their errors correlate instead of averaging out.
        """
        xc = x.reshape(-1, self.chunk)
        amax = jnp.max(jnp.abs(xc), axis=1, keepdims=True)
        scale = jnp.where(amax > 0, amax / self.qmax, 1.0)
        if self.dtype == "int8":
            y = xc / scale
            if self.stochastic_rounding and key is not None:
                import jax  # deferred: keep module import-light

                y = jnp.floor(y + jax.random.uniform(key, y.shape))
            else:
                y = jnp.round(y)
            q = jnp.clip(y, -self.qmax, self.qmax).astype(jnp.int8)
        else:
            # fp8 cast rounds; clip first so overflow saturates predictably
            q = jnp.clip(xc / scale, -self.qmax,
                         self.qmax).astype(self.wire_dtype)
        return q, scale

    def dequantize(self, q, scale) -> jnp.ndarray:
        return (q.astype(jnp.float32) * scale).reshape(-1)

    # -------------------------------------------------- inter-hop allreduce
    def inter_allreduce(self, x, axis_name: str, key=None):
        """Quantized allreduce over `axis_name` via all-gather: the wire
        carries the quantized blocks (the HLO's all-gather operand dtype
        IS the wire dtype); dequant + f32 accumulation happen locally in
        source-rank order. Fused/TPU lowering — one shard_map program."""
        q, scale = self.quantize(x, key=key)
        qg = lax.all_gather(q, axis_name)        # [world, nc, chunk] wire dtype
        sg = lax.all_gather(scale, axis_name)    # [world, nc, 1] f32 (tiny)
        return (qg.astype(jnp.float32) * sg).sum(axis=0).reshape(x.shape)

    def inter_allreduce_ef(self, x, residual, axis_name: str, key=None):
        """Error-feedback variant: returns (reduced, new_residual)."""
        xc = x + residual
        q, scale = self.quantize(xc, key=key)
        new_residual = xc - self.dequantize(q, scale).reshape(x.shape)
        qg = lax.all_gather(q, axis_name)
        sg = lax.all_gather(scale, axis_name)
        out = (qg.astype(jnp.float32) * sg).sum(axis=0).reshape(x.shape)
        return out, new_residual

    def ring_allreduce(self, x, axis_name: str, world: int,
                       residual: Optional[jnp.ndarray] = None, key=None):
        """Quantized allreduce over `axis_name` via a ppermute ring.

        Same wire bytes as the gather form, but lowered as world-1
        CollectivePermute rounds — the faster lowering where the
        transport's all-gather is weak (the CPU/gloo incarnation; gloo
        all-gather measured ~5x slower than ppermute at equal bytes).

        The quantized payload and its f32 scales ship as ONE packed int8
        buffer per hop (scales bitcast into the tail): two independent
        collectives in one program may execute CONCURRENTLY on the same
        transport pair, and gloo cross-pairs their frames (observed as
        `op.preamble.length <= op.nbytes` aborts) — a single buffer per
        round leaves nothing to mispair.

        Contributions are collected into a [world, ...] buffer indexed by
        SOURCE rank and summed in that fixed order, so every member
        computes the bit-identical f32 result. Returns `reduced` or
        (reduced, new_residual) when `residual` is given.
        """
        from jax import lax as _lax

        from ray_tpu.util.collective.hierarchy import ring_perm

        xc = x if residual is None else x + residual
        q, scale = self.quantize(xc, key=key)
        if residual is not None:
            new_residual = xc - self.dequantize(q, scale).reshape(x.shape)
        nc, C = q.shape
        qb = (q if q.dtype == jnp.int8
              else _lax.bitcast_convert_type(q, jnp.int8))
        sb = _lax.bitcast_convert_type(scale, jnp.int8).reshape(nc, 4)
        pack = jnp.concatenate([qb, sb], axis=1)      # [nc, C+4] int8
        idx = lax.axis_index(axis_name)
        buf = jnp.zeros((world,) + pack.shape, jnp.int8)
        buf = lax.dynamic_update_index_in_dim(buf, pack, idx, 0)
        perm = ring_perm(world)
        cur, src = pack, idx
        for _ in range(world - 1):
            cur = lax.ppermute(cur, axis_name, perm)
            src = (src - 1) % world
            buf = lax.dynamic_update_index_in_dim(buf, cur, src, 0)
        qg = buf[:, :, :C]
        if self.dtype != "int8":
            qg = _lax.bitcast_convert_type(qg, self.wire_dtype)
        sg = _lax.bitcast_convert_type(
            buf[:, :, C:].reshape(world, nc, 1, 4), jnp.float32)
        out = (qg.astype(jnp.float32) * sg.reshape(world, nc, 1)).sum(
            axis=0).reshape(x.shape)
        if residual is None:
            return out
        return out, new_residual


__all__ = ["QuantizedAllreduce"]
