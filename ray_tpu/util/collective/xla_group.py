"""In-process device collective group: eager collectives over a jax Mesh.

This is the TPU-native replacement for the reference's NCCL group
(`python/ray/util/collective/collective_group/nccl_collective_group.py:128`):
one process drives N local chips (ranks = devices), and each collective is a
jit-compiled shard_map program whose data plane is XLA collectives riding ICI.
There are no communicator handles or streams to manage — XLA owns scheduling.

The primary use is API parity for eager multi-device code (the reference's
`allreduce_multigpu` shape: one tensor per local device). High-performance
training should instead express parallelism as shardings inside one pjit
program (ray_tpu.parallel) so collectives fuse with compute; this group is
for the cases Ray users reach for ray.util.collective today.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from ray_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.util.collective.types import ReduceOp

_AXIS = "ranks"


class XlaCollectiveGroup:
    backend_name = "xla"

    def __init__(self, devices: Optional[Sequence] = None,
                 group_name: str = "default"):
        self.devices = list(devices) if devices is not None else jax.devices()
        self.group_name = group_name
        self.world_size = len(self.devices)
        self.mesh = Mesh(np.array(self.devices), (_AXIS,))
        self._sharding = NamedSharding(self.mesh, P(_AXIS))

    # --------------------------------------------------------------- helpers
    def _stack(self, tensors: Sequence) -> jax.Array:
        """One tensor per rank -> global array sharded over the rank axis."""
        if len(tensors) != self.world_size:
            raise ValueError(
                f"need {self.world_size} tensors (one per device), got "
                f"{len(tensors)}")
        shards = [
            jax.device_put(jnp.expand_dims(jnp.asarray(t), 0), d)
            for t, d in zip(tensors, self.devices)
        ]
        shape = (self.world_size, *shards[0].shape[1:])
        return jax.make_array_from_single_device_arrays(
            shape, self._sharding, shards)

    @staticmethod
    def _unstack(x: jax.Array) -> List[jax.Array]:
        shards = sorted(x.addressable_shards, key=lambda s: s.index[0].start)
        return [s.data[0] for s in shards]

    @functools.lru_cache(maxsize=None)
    def _allreduce_fn(self, op: ReduceOp):
        if op is ReduceOp.SUM:
            body = lambda x: jax.lax.psum(x, _AXIS)
        elif op is ReduceOp.MAX:
            body = lambda x: jax.lax.pmax(x, _AXIS)
        elif op is ReduceOp.MIN:
            body = lambda x: jax.lax.pmin(x, _AXIS)
        else:
            # PRODUCT: XLA has no pprod primitive — all-gather the factors
            # and multiply. The gather materializes a [world, ...]
            # intermediate, so it runs CHUNKED (32 MiB gather cap via
            # hierarchy.gathered_reduce) instead of asking for
            # world x leaf bytes on large leaves.
            from ray_tpu.util.collective.hierarchy import gathered_reduce

            def body(x):
                return jnp.expand_dims(gathered_reduce(
                    x[0], _AXIS, lambda g: g.prod(axis=0)), 0)

        return jax.jit(shard_map(body, mesh=self.mesh, in_specs=P(_AXIS),
                                 out_specs=P(_AXIS)))

    @functools.cached_property
    def _reducescatter_fn(self):
        # per-shard block is [1, world, ...]; scatter over the contribution
        # axis so rank r keeps the reduced row r, then restore the rank axis
        return jax.jit(shard_map(
            lambda x: jnp.expand_dims(
                jax.lax.psum_scatter(x[0], _AXIS, tiled=False), 0),
            mesh=self.mesh, in_specs=P(_AXIS), out_specs=P(_AXIS)))

    @functools.cached_property
    def _allgather_fn(self):
        return jax.jit(shard_map(
            lambda x: jax.lax.all_gather(x[0], _AXIS),
            mesh=self.mesh, in_specs=P(_AXIS), out_specs=P(),
            check_vma=False))

    @functools.lru_cache(maxsize=None)
    def _ppermute_fn(self, perm: tuple):
        return jax.jit(shard_map(
            lambda x: jax.lax.ppermute(x, _AXIS, perm=list(perm)),
            mesh=self.mesh, in_specs=P(_AXIS), out_specs=P(_AXIS)))

    # ------------------------------------------------------------ collectives
    def allreduce(self, tensors: Sequence, op: ReduceOp = ReduceOp.SUM):
        out = self._allreduce_fn(op)(self._stack(tensors))
        return self._unstack(out)

    def reduce(self, tensors: Sequence, dst_rank: int = 0,
               op: ReduceOp = ReduceOp.SUM):
        full = self.allreduce(tensors, op)
        return [full[i] if i == dst_rank else tensors[i]
                for i in range(self.world_size)]

    def broadcast(self, tensors: Sequence, src_rank: int = 0):
        perm = tuple((src_rank, d) for d in range(self.world_size))
        # one-to-all: gather is simplest and XLA lowers it to an ICI broadcast
        x = self._stack(tensors)
        full = self._allgather_fn(x)  # replicated [world, ...]
        src = full[src_rank]
        return [jax.device_put(src, d) for d in self.devices]

    def allgather(self, tensors: Sequence) -> List[List[jax.Array]]:
        full = self._allgather_fn(self._stack(tensors))
        return [[jax.device_put(full[r], d) for r in range(self.world_size)]
                for d in self.devices]

    def reducescatter(self, tensors: Sequence, op: ReduceOp = ReduceOp.SUM):
        """Each rank contributes [world, ...]; rank r receives reduced row r."""
        if op is not ReduceOp.SUM:
            red = self.allreduce([jnp.asarray(t) for t in tensors], op)
            return [red[r][r] for r in range(self.world_size)]
        stacked = self._stack(tensors)  # [world, world, ...]
        out = self._reducescatter_fn(stacked)
        return [s.data[0] for s in sorted(out.addressable_shards,
                                          key=lambda s: s.index[0].start)]

    def barrier(self):
        jax.block_until_ready(
            self.allreduce([jnp.zeros(()) for _ in self.devices]))

    def send_recv(self, tensors: Sequence, pairs: Sequence[tuple]):
        """ppermute: pairs is a list of (src_rank, dst_rank)."""
        out = self._ppermute_fn(tuple(pairs))(self._stack(tensors))
        return self._unstack(out)

    def destroy(self):
        self._allreduce_fn.cache_clear()
        self._ppermute_fn.cache_clear()
