"""Cross-process device collective group: `jax.distributed` sub-cluster.

Parity target: the reference's NCCL collective group
(`python/ray/util/collective/collective_group/nccl_collective_group.py:128`)
— N actor PROCESSES form a gang whose collectives run on the device plane.
TPU-native shape: rendezvous through the head KV (the reference stores the
NCCL uniqueId in a named actor), then `jax.distributed.initialize` welds
the member processes into one JAX cluster; a global 1-device-per-process
mesh is built and collectives execute as `shard_map` programs over it, so
the data plane is XLA's ICI/DCN collectives — not host relays.

p2p send/recv run the device plane too: the two peers build a 2-device
pair mesh (their devices only) and execute one `lax.ppermute` program —
the XLA CollectivePermute equivalent of NCCL Send/Recv
(`collective.py:584-705`). Broadcast is a one-to-many ppermute on the full
mesh (src transmits world-1 copies — a real broadcast, not the 2x-traffic
zeros-allreduce). Reduce keeps the psum lowering: on a ring, reduce and
allreduce move the same bytes, and XLA exposes no pairwise-accumulate
primitive that would beat it.

CI story (SURVEY §4.2 pattern 3): on CPU the same code runs with the gloo
CPU-collectives implementation and `--xla_force_host_platform_device_count=1`
per process — the fake-backend pattern the reference uses for NCCL tests.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.util.collective.types import ReduceOp
from ray_tpu.utils.jax_compat import shard_map as _compat_shard_map

_COORD_NS = "collective_xmh"
_MEMBER_NS = "collective_xmh_members"
_POLL_S = 0.05


def _reduce_op(op: ReduceOp):
    from jax import lax

    def pprod(a, ax):
        # XLA has no pprod primitive: all-gather the factors and multiply.
        # The gather materializes a [world, ...] intermediate, so it runs
        # CHUNKED (hierarchy.gathered_reduce): peak extra memory is the
        # 32 MiB cap + one chunk's product, not world x leaf bytes —
        # a naive gather of a 1 GiB leaf at world=64 would ask for 64 GiB.
        from ray_tpu.util.collective.hierarchy import gathered_reduce

        return gathered_reduce(a, ax, lambda g: g.prod(axis=0))

    return {ReduceOp.SUM: lambda a, ax: lax.psum(a, ax),
            ReduceOp.MAX: lambda a, ax: lax.pmax(a, ax),
            ReduceOp.MIN: lambda a, ax: lax.pmin(a, ax),
            ReduceOp.PRODUCT: pprod}[op]


def _rs_program(op: ReduceOp):
    """Per-shard reduce-scatter body over mesh axis "p"; factored out so
    tests can lower it on a local mesh and assert the HLO really is a
    reduce-scatter, not a full allreduce."""
    from jax import lax

    if op is ReduceOp.SUM:
        def fn(a):  # a: [1, world, ...] local block
            return lax.psum_scatter(a[0], "p", scatter_dimension=0,
                                    tiled=True)
        return fn
    red = _reduce_op(op)

    def fn(a):
        # non-sum ops have no scatter primitive in XLA: reduce, then
        # slice inside the program (the compiler sees the slice)
        full = red(a[0], "p")               # [world, ...]
        idx = lax.axis_index("p")
        return lax.dynamic_index_in_dim(full, idx, 0, keepdims=True)
    return fn


class XlaMultihostGroup:
    """One member process of a cross-process device collective gang."""

    backend_name = "xla-multihost"

    def __init__(self, client, group_name: str, world_size: int, rank: int,
                 timeout_s: float = 60.0):
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} out of range for world {world_size}")
        self._client = client
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        # collective launches are per-process serialized (NCCL-style: two
        # threads interleaving programs on one group would mismatch the
        # SPMD program order across members)
        import threading

        self._op_lock = threading.Lock()
        self._init_jax_cluster(timeout_s)
        self._publish_membership()

    # ------------------------------------------------------------ rendezvous
    def _coord_key(self) -> bytes:
        return f"{self.group_name}:coordinator".encode()

    def _init_jax_cluster(self, timeout_s: float) -> None:
        import jax

        # env check ONLY — jax.default_backend() would initialize XLA,
        # which must not happen before jax.distributed.initialize
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            # the reference's mock-NCCL pattern: same code path, CPU gloo
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        if self.rank == 0:
            # a leftover key from a crashed same-named group is deleted
            # here, BEFORE members can read it — liveness by generation,
            # not by comparing wall clocks across hosts (clock skew made
            # fresh keys look stale). The key itself is deleted again once
            # everyone has joined and on destroy().
            try:
                self._client.kv_del(_COORD_NS, self._coord_key())
            except Exception:
                pass
            addr = self._start_coordinator(timeout_s)
        else:
            deadline = time.monotonic() + timeout_s
            addr = None
            while True:
                blob = self._client.kv_get(_COORD_NS, self._coord_key())
                if blob:
                    cand = pickle.loads(blob)["addr"]
                    # liveness probe: a leftover key from a crashed group
                    # (read before rank 0's delete) or an abandoned
                    # bind-retry port refuses the connection — keep
                    # polling until a LIVE coordinator answers, instead of
                    # hanging initialize against a dead address
                    if self._probe(cand):
                        addr = cand
                        break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"group {self.group_name}: no live coordinator "
                        f"within {timeout_s}s")
                time.sleep(_POLL_S)
            self._ensure_jax_distributed(addr)
        if self.rank == 0:
            # initialize() returns once every process has joined — the
            # rendezvous key has served its purpose
            try:
                self._client.kv_del(_COORD_NS, self._coord_key())
            except Exception:
                pass
        import jax
        from jax.sharding import Mesh

        from ray_tpu.util.collective.hierarchy import (Topology,
                                                       device_rows_by_process)

        rows = device_rows_by_process(jax.devices())
        if len(rows) != self.world_size:
            raise RuntimeError(
                f"jax cluster has {len(rows)} processes, expected "
                f"{self.world_size}")
        devs = [row[0] for row in rows]
        self.mesh = Mesh(np.array(devs), ("p",))
        self._rank_dev = devs
        self._local_dev = rows[jax.process_index()][0]
        self._pair_meshes: Dict[Tuple[int, int], Any] = {}
        # hosts x local-devices hierarchy: every member process is one
        # "host" row; its local virtual/physical devices are the intra
        # (fast-fabric) axis. Asymmetric device counts truncate to the
        # common minimum so the 2D mesh stays rectangular.
        n_local = min(len(r) for r in rows)
        self.topology = Topology(inter=self.world_size, intra=n_local)
        self._local_devs = rows[jax.process_index()][:n_local]
        self._hier_mesh = Mesh(
            np.array([r[:n_local] for r in rows]),
            (self.topology.inter_axis, self.topology.intra_axis))
        self._hier_progs: Dict[Tuple, Any] = {}
        self._ef_state: Dict[Tuple, Any] = {}

    @staticmethod
    def _probe(addr: str) -> bool:
        import socket

        host, port = addr.rsplit(":", 1)
        try:
            with socket.create_connection((host, int(port)), timeout=1.0):
                return True
        except OSError:
            return False

    def _start_coordinator(self, timeout_s: float) -> str:
        """Rank 0: publish an address, then bind the coordinator inside
        jax.distributed.initialize. The free-port probe is only a hint —
        if the port is taken between probe and bind (TOCTOU), we re-pick
        a port, re-publish, and retry instead of failing."""
        import socket

        host = os.environ.get("RAY_TPU_NODE_IP", "127.0.0.1")
        last = None
        for _ in range(3):
            with socket.socket() as s:
                s.bind(("", 0))
                port = s.getsockname()[1]
            addr = f"{host}:{port}"
            self._client.kv_put(
                _COORD_NS, self._coord_key(),
                pickle.dumps({"addr": addr, "nonce": os.urandom(8).hex()}),
                overwrite=True)
            try:
                self._ensure_jax_distributed(addr)
                return addr
            except RuntimeError as e:
                # bind race lost: retry with a fresh port. Anything else
                # (geometry mismatch, member crash) propagates.
                if "bind" not in str(e).lower():
                    raise
                last = e
        raise RuntimeError(
            f"group {self.group_name}: coordinator could not bind "
            f"after 3 attempts: {last}")

    def _ensure_jax_distributed(self, addr: str) -> None:
        """Join (or reuse) this process's jax.distributed cluster.

        initialize() is once-per-process; a second group in the same
        process reuses the existing cluster when its geometry matches
        (process count == world_size, our index == rank) and fails loudly
        otherwise — never with jax's opaque 'already initialized' error."""
        import jax
        from jax._src import distributed as jdist

        state = getattr(jdist, "global_state", None)
        if state is not None and state.client is not None:
            if (jax.process_count() != self.world_size
                    or jax.process_index() != self.rank):
                raise RuntimeError(
                    f"group {self.group_name}: this process already belongs "
                    f"to a jax.distributed cluster of "
                    f"{jax.process_count()} processes (as index "
                    f"{jax.process_index()}) — an xla-multihost group must "
                    f"match it (asked world={self.world_size} "
                    f"rank={self.rank})")
            return
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=self.world_size,
                                   process_id=self.rank)

    def _publish_membership(self) -> None:
        """worker-id -> (group, rank) in the head KV: lets the device
        object store route a get() between gang members over the ICI
        data plane instead of host staging. Also carries this member's
        topology coordinates (host identity + local device count) so
        `hierarchy.infer_topology` can group the gang into hosts x local
        devices without extra RPCs."""
        try:
            wid = self._client.worker_id.hex()
            host = os.environ.get("RAY_TPU_NODE_IP") or (
                __import__("socket").gethostname())
            self._client.kv_put(
                _MEMBER_NS, wid.encode(),
                pickle.dumps({"group": self.group_name, "rank": self.rank,
                              "world": self.world_size, "host": host,
                              "local_devices": self.topology.intra}),
                overwrite=True)
        except Exception:
            pass  # membership routing is an optimization, never fatal

    # ------------------------------------------------------------- data plane
    def _global(self, x: np.ndarray):
        """Local array -> global [world, ...] jax.Array, one shard per
        process, sharded over the mesh's `p` axis."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = np.ascontiguousarray(x)
        sharding = NamedSharding(self.mesh, P("p", *([None] * x.ndim)))
        local = jax.device_put(x[None], self._local_dev)
        return jax.make_array_from_single_device_arrays(
            (self.world_size,) + x.shape, sharding, [local])

    def _shard_map(self, fn, g):
        import jax
        from jax.sharding import PartitionSpec as P

        return _compat_shard_map(fn, mesh=self.mesh, in_specs=P("p"),
                             out_specs=P("p"))(g)

    def _local_of(self, garr) -> np.ndarray:
        """This process's shard of a [world, ...] global array."""
        shard = garr.addressable_shards[0]
        return np.asarray(shard.data)[0]

    # ------------------------------------------------------------ collectives
    def _allreduce_np(self, x: np.ndarray, op: ReduceOp) -> np.ndarray:
        red = _reduce_op(op)
        out = self._shard_map(lambda a: red(a, "p"), self._global(x))
        return self._local_of(out)

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM, timeout=None):
        from ray_tpu.util.collective.kv_group import _write_back

        # in-place semantics match the kv/reference backends: the caller's
        # tensor holds the reduced value afterwards
        with self._op_lock:
            out = self._allreduce_np(np.asarray(tensor), op)
        return _write_back(tensor, out)

    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM,
               timeout=None):
        """Reduce-to-one, lowered as psum: on a ring interconnect a reduce
        moves the same bytes as an allreduce (reduce-scatter phase is
        identical; the gather phase converges on dst), and XLA exposes no
        cheaper pairwise-accumulate — so this is bandwidth-optimal, not a
        shortcut."""
        from ray_tpu.util.collective.kv_group import _write_back

        with self._op_lock:
            out = self._allreduce_np(np.asarray(tensor), op)
        if self.rank == dst_rank:
            return _write_back(tensor, out)
        return tensor

    def broadcast(self, tensor, src_rank: int = 0, timeout=None):
        """Binomial-tree broadcast: ceil(log2(world)) ppermute rounds with
        unique (src,dst) pairs per round. Moves (world-1)·size bytes total
        at log depth — a real broadcast lowering, not the old 2x-traffic
        zeros-allreduce."""
        import jax.numpy as jnp
        from jax import lax

        from ray_tpu.util.collective.kv_group import _write_back

        x = np.asarray(tensor)
        world, src = self.world_size, src_rank

        def real(v):  # virtual rank (src-rooted) -> mesh rank
            return (v + src) % world

        def fn(a):
            idx = lax.axis_index("p")
            v = (idx - src) % world
            step = 1
            while step < world:
                pairs = [(real(i), real(i + step))
                         for i in range(step) if i + step < world]
                moved = lax.ppermute(a, "p", pairs)
                is_dst = jnp.logical_and(v >= step, v < 2 * step)
                a = jnp.where(is_dst, moved, a)
                step *= 2
            return a

        with self._op_lock:
            out = self._shard_map(fn, self._global(x))
            local = self._local_of(out)
        return _write_back(tensor, local)

    def allgather(self, tensor, timeout=None) -> List[np.ndarray]:
        from jax import lax

        x = np.asarray(tensor)
        with self._op_lock:
            out = self._shard_map(
                lambda a: lax.all_gather(a[0], "p")[None], self._global(x))
            gathered = self._local_of(out)  # [world, ...]
        return [gathered[i] for i in range(self.world_size)]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM, timeout=None):
        """Input [world, ...]; returns this rank's reduced slice.

        SUM lowers to `lax.psum_scatter` INSIDE the shard_map program —
        a true reduce-scatter moving ~1/world of the allreduce bytes
        (slicing on the host after a full psum would force XLA to
        materialize and ship the whole reduced tensor to every rank).
        Reference semantics: `util/collective/collective.py:525`."""
        arr = np.asarray(tensor)
        if arr.shape[0] != self.world_size:
            raise ValueError(
                f"reducescatter input leading dim {arr.shape[0]} != world "
                f"{self.world_size}")

        with self._op_lock:
            out = self._shard_map(_rs_program(op), self._global(arr))
            return self._local_of(out)

    # --------------------------------------- hierarchical device-plane path
    def _hier_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        t = self.topology
        return NamedSharding(self._hier_mesh,
                             P((t.inter_axis, t.intra_axis)))

    def _hier_program(self, colpad: int, op: ReduceOp, quantize,
                      average: bool):
        """Compiled inter-hop program for one (column size, op, quant)
        shape; cached per group. The intra phases of the staged schedule
        (scatter to columns / regather) happen OUTSIDE the program on the
        host-local fabric — under a distributed CPU runtime every
        in-program collective pays the cross-process transport, so only
        the genuinely inter-host hop runs as a collective. On the fused
        TPU path (`hierarchy.hier_allreduce_program`) all three phases
        stay in one program."""
        ef = (quantize is not None and quantize.error_feedback
              and op is ReduceOp.SUM)
        key = (colpad, op, quantize.key() if quantize else None, average)
        prog = self._hier_progs.get(key)
        if prog is not None:
            return prog
        import jax
        from jax.sharding import PartitionSpec as P

        t = self.topology
        H, inter = t.inter, t.inter_axis
        spec = P((t.inter_axis, t.intra_axis))
        # divide (not multiply-by-reciprocal): the kv fallback divides its
        # host buffer, and grad-sync parity across backends must be exact
        world = self.world_size if average else 0
        red = _reduce_op(op)

        if quantize is not None and op is ReduceOp.SUM:
            if ef:
                def body(a, r):
                    out, nr = quantize.ring_allreduce(
                        a[0], inter, H, residual=r[0])
                    if world:
                        out = out / world
                    return out[None], nr[None]

                fn = _compat_shard_map(
                    body, mesh=self._hier_mesh, in_specs=(spec, spec),
                    out_specs=(spec, spec), check_vma=False)
            else:
                def body(a):
                    out = quantize.ring_allreduce(a[0], inter, H)
                    if world:
                        out = out / world
                    return out[None]

                fn = _compat_shard_map(body, mesh=self._hier_mesh,
                                       in_specs=spec, out_specs=spec,
                                       check_vma=False)
        else:
            def body(a):
                out = red(a[0], inter)
                if world:
                    out = out / world
                return out[None]

            fn = _compat_shard_map(body, mesh=self._hier_mesh,
                                   in_specs=spec, out_specs=spec,
                                   check_vma=False)
        prog = jax.jit(fn)
        self._hier_progs[key] = prog
        return prog

    def allreduce_device(self, tensor, op: ReduceOp = ReduceOp.SUM, *,
                         quantize=None, average: bool = False,
                         ef_key: str = "", timeout=None):
        """Hierarchical device-plane allreduce of a FLOATING tensor;
        returns a jax.Array on this process's first local device (input
        is NOT mutated — device consumers chain off the returned array).
        Integer payloads must use the flat `allreduce` (this path stages
        through f32 and would corrupt values above 2^24).

        Staged two-level schedule over `self.topology` (hosts x local
        devices): the payload is split into `intra` columns, one per
        local device; each column allreduces its S/intra shard across the
        `inter` (host) axis CONCURRENTLY — the slow fabric carries S/intra
        per link instead of S — and the columns regather on the local
        fabric. With `quantize`, the inter hop runs the int8/fp8 ppermute
        ring with per-chunk scales. Error-feedback residuals persist on
        device between calls, keyed by (`ef_key`, payload size, quant
        config): callers syncing SEVERAL same-sized logical buffers must
        pass a distinct `ef_key` per buffer, or their residuals
        cross-contaminate (each call would fold the OTHER buffer's
        leftover quantization error into its sum). One residual buffer is
        retained per distinct key for the life of the group.

        `timeout` is accepted for kv-API parity but NOT enforced: like
        every device-plane collective here, the gloo/ICI program blocks
        until all members enter it, so a dead peer hangs the call — gang
        death is the controller's job (the PR 6 death watch fences and
        rebuilds the group; the kv fallback is the path with a real
        deadline)."""
        import jax
        import jax.numpy as jnp

        t = self.topology
        H, L = t.inter, t.intra
        x = np.asarray(tensor)
        shape, orig_dtype, n = x.shape, x.dtype, x.size
        if orig_dtype.kind != "f":
            raise TypeError(
                f"allreduce_device needs a floating dtype, got "
                f"{orig_dtype}; integer tensors take the flat allreduce()")
        if orig_dtype.itemsize > 4:
            raise TypeError(
                f"allreduce_device stages through f32 and would silently "
                f"truncate {orig_dtype} precision; use the flat "
                f"allreduce() (dtype-preserving) or downcast explicitly")
        if quantize is not None and op is not ReduceOp.SUM:
            raise ValueError(
                f"quantized allreduce supports SUM only (got {op.name}): "
                f"the int8/fp8 exchange accumulates contributions in f32 "
                f"source-rank order, which has no analog for other "
                f"reductions — drop quantize= for {op.name}")
        colpad = -(-max(n, 1) // L)
        if quantize is not None:
            colpad = quantize.padded_size(colpad)
        flat = np.zeros(L * colpad, dtype=np.float32)
        flat[:n] = np.ravel(x)
        cols = flat.reshape(L, colpad)
        ef = (quantize is not None and quantize.error_feedback
              and op is ReduceOp.SUM)
        gshard = self._hier_sharding()
        with self._op_lock:
            puts = [jax.device_put(cols[i][None], d)
                    for i, d in enumerate(self._local_devs)]
            ga = jax.make_array_from_single_device_arrays(
                (H * L, colpad), gshard, puts)
            prog = self._hier_program(colpad, op, quantize, average)
            if ef:
                rkey = (ef_key, colpad, quantize.key())
                r = self._ef_state.get(rkey)
                if r is None:
                    zeros = [jax.device_put(
                        np.zeros((1, colpad), np.float32), d)
                        for d in self._local_devs]
                    r = jax.make_array_from_single_device_arrays(
                        (H * L, colpad), gshard, zeros)
                out, self._ef_state[rkey] = prog(ga, r)
            else:
                out = prog(ga)
            parts = sorted(out.addressable_shards,
                           key=lambda s: s.index[0].start)
            col_arrs = [jax.device_put(s.data[0], self._local_devs[0])
                        for s in parts]
            fused = (jnp.concatenate(col_arrs) if len(col_arrs) > 1
                     else col_arrs[0])
        self._account_hier(op, colpad, quantize)
        res = fused[:n].reshape(shape)
        if orig_dtype.kind == "f" and res.dtype != orig_dtype:
            res = res.astype(orig_dtype)
        return res

    def _account_hier(self, op: ReduceOp, colpad: int, quantize) -> None:
        from ray_tpu.util.collective import hierarchy as _hier

        t = self.topology
        fp32_wire = 2 * (t.inter - 1) * colpad * 4 * t.intra // max(t.inter, 1)
        if quantize is not None and op is ReduceOp.SUM:
            wire = (t.inter - 1) * quantize.wire_bytes(colpad) * t.intra
            _hier.account_collective("allreduce", wire,
                                     quantize.dtype, hop="inter")
            _hier.account_quant_saving(max(0, fp32_wire - wire))
        else:
            _hier.account_collective("allreduce", fp32_wire, "float32",
                                     hop="inter")
        if t.intra > 1:
            # scatter + regather columns on the host-local fabric
            _hier.account_collective("allreduce", 2 * t.intra * colpad * 4,
                                     "float32", hop="intra")

    def allreduce_tree(self, tree, *, average: bool = True, quantize=None,
                       timeout=None):
        """Fused device-plane gradient sync: flatten the pytree's leaves
        into one f32 buffer, run ONE hierarchical allreduce, unflatten.
        Cross-member bytes ride the gang's device transport (ICI/DCN on
        TPU, gloo here) — the head KV carries nothing (the kv collective
        is the CPU-only fallback, see train.spmd.cross_worker_grad_sync).
        `timeout` is not enforced on the device plane (see
        `allreduce_device`); leaves are staged through f32 (f64 leaves
        lose precision — keep f64 state on the kv path)."""
        import jax
        import jax.numpy as jnp

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            return tree
        arrs = [np.asarray(leaf) for leaf in leaves]
        fused = np.concatenate(
            [a.ravel().astype(np.float32, copy=False) for a in arrs])
        out = np.asarray(self.allreduce_device(
            fused, ReduceOp.SUM, quantize=quantize, average=average))
        res, off = [], 0
        for a, leaf in zip(arrs, leaves):
            dt = getattr(leaf, "dtype", a.dtype)
            res.append(jnp.asarray(
                out[off:off + a.size].reshape(a.shape), dtype=dt))
            off += a.size
        return jax.tree_util.tree_unflatten(treedef, res)

    def barrier(self, timeout=None):
        from jax.experimental import multihost_utils

        # name must be IDENTICAL on every process (it is hashed and
        # compared); a per-group counter keeps successive barriers distinct
        with self._op_lock:
            self._barrier_seq = getattr(self, "_barrier_seq", 0) + 1
            multihost_utils.sync_global_devices(
                f"{self.group_name}:barrier:{self._barrier_seq}")

    # ------------------------------------------------------------------- p2p
    def _pair_mesh(self, src: int, dst: int):
        from jax.sharding import Mesh

        key = (src, dst)
        mesh = self._pair_meshes.get(key)
        if mesh is None:
            mesh = Mesh(np.array([self._rank_dev[src], self._rank_dev[dst]]),
                        ("pp",))
            self._pair_meshes[key] = mesh
        return mesh

    def _p2p_program(self, local_arr, src: int, dst: int):
        """Both peers execute ONE ppermute program on their 2-device pair
        mesh: src's shard moves to dst's device over the interconnect
        (ICI/DCN on TPU, gloo on the CPU CI incarnation). `local_arr` may
        be a jax.Array already resident on our device (no host bounce) or
        a numpy array (one H2D). Returns the receiver-side output STILL ON
        DEVICE so device consumers never round-trip host.

        Like NCCL Send/Recv, a pair program blocks until BOTH peers enter
        it and cannot be preempted — a dead peer hangs the call, and the
        relative order of programs launched on one group must match on
        every participating member (hence `_op_lock`)."""
        import jax
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._pair_mesh(src, dst)
        if isinstance(local_arr, jax.Array):
            local = local_arr[None]          # stays on its (our) device
            shape = tuple(local_arr.shape)
        else:
            x = np.ascontiguousarray(local_arr)
            local = jax.device_put(x[None], self._local_dev)
            shape = x.shape
        sharding = NamedSharding(mesh, P("pp", *([None] * len(shape))))
        # exactly the addressable shards of THIS process (one of the two)
        g = jax.make_array_from_single_device_arrays(
            (2,) + shape, sharding, [local])
        out = _compat_shard_map(
            lambda a: lax.ppermute(a, "pp", [(0, 1)]),
            mesh=mesh, in_specs=P("pp"), out_specs=P("pp"))(g)
        return out.addressable_shards[0].data  # [1, ...] on local device

    def send(self, tensor, dst_rank: int, timeout=None):
        """NCCL-parity p2p: blocks until the peer enters recv; `timeout`
        is accepted for API parity but a device-plane collective cannot be
        preempted once launched (same as the reference's NCCL backend)."""
        if dst_rank == self.rank:
            raise ValueError("send to self")
        with self._op_lock:
            self._p2p_program(np.asarray(tensor), self.rank, dst_rank)

    def recv(self, tensor, src_rank: int, timeout=None):
        from ray_tpu.util.collective.kv_group import _write_back

        if src_rank == self.rank:
            raise ValueError("recv from self")
        buf = np.asarray(tensor)
        with self._op_lock:
            out = self._p2p_program(np.zeros_like(buf), src_rank, self.rank)
        return _write_back(tensor, np.asarray(out)[0])

    def send_device(self, leaf, dst_rank: int):
        """Device-plane send of a jax leaf (device-object ICI fetch): the
        leaf feeds the pair mesh directly from HBM — no D2H/H2D bounce.

        Bounded lock acquire: if this process is wedged in another
        collective (e.g. a mutual bidirectional fetch — a known ordering
        hazard shared with NCCL p2p), fail loudly instead of deadlocking
        the executor thread forever."""
        if not self._op_lock.acquire(timeout=120):
            raise TimeoutError(
                f"group {self.group_name}: collective order lock held for "
                ">120s — concurrent conflicting collectives on this group")
        try:
            self._p2p_program(leaf, self.rank, dst_rank)
        finally:
            self._op_lock.release()

    def recv_device(self, shape, dtype, src_rank: int):
        """Device-plane recv returning a jax.Array on our device."""
        with self._op_lock:
            out = self._p2p_program(np.zeros(shape, dtype=dtype),
                                    src_rank, self.rank)
        return out[0]

    def destroy(self):
        try:
            self._client.kv_del(_MEMBER_NS,
                                self._client.worker_id.hex().encode())
        except Exception:
            pass
        if self.rank == 0:
            try:
                self._client.kv_del(_COORD_NS, self._coord_key())
            except Exception:
                pass


def lookup_membership(client, worker_id_hex: str) -> Optional[dict]:
    """Head-KV lookup: is `worker_id` a live gang member? Used by the
    device object store to pick the ICI path between gang peers."""
    try:
        blob = client.kv_get(_MEMBER_NS, worker_id_hex.encode())
    except Exception:
        return None
    if not blob:
        return None
    return pickle.loads(blob)
