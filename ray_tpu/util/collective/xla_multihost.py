"""Cross-process device collective group: `jax.distributed` sub-cluster.

Parity target: the reference's NCCL collective group
(`python/ray/util/collective/collective_group/nccl_collective_group.py:128`)
— N actor PROCESSES form a gang whose collectives run on the device plane.
TPU-native shape: rendezvous through the head KV (the reference stores the
NCCL uniqueId in a named actor), then `jax.distributed.initialize` welds
the member processes into one JAX cluster; a global 1-device-per-process
mesh is built and collectives execute as `shard_map` programs over it, so
the data plane is XLA's ICI/DCN collectives — not host relays.

CI story (SURVEY §4.2 pattern 3): on CPU the same code runs with the gloo
CPU-collectives implementation and `--xla_force_host_platform_device_count=1`
per process — the fake-backend pattern the reference uses for NCCL tests.

p2p send/recv are host-staged through the KV store for now: XLA exposes
ppermute (a full collective) but no pairwise primitive; a device-plane p2p
rides the same mesh once ICI send/recv lands.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import List, Optional

import numpy as np

from ray_tpu.util.collective.types import ReduceOp

_COORD_NS = "collective_xmh"
_POLL_S = 0.05


def _reduce_op(op: ReduceOp):
    from jax import lax

    def pprod(a, ax):
        # XLA has no pprod primitive: all-gather the factors and multiply
        g = lax.all_gather(a, ax)          # [world, ...]
        return g.prod(axis=0)

    return {ReduceOp.SUM: lambda a, ax: lax.psum(a, ax),
            ReduceOp.MAX: lambda a, ax: lax.pmax(a, ax),
            ReduceOp.MIN: lambda a, ax: lax.pmin(a, ax),
            ReduceOp.PRODUCT: pprod}[op]


class XlaMultihostGroup:
    """One member process of a cross-process device collective gang."""

    backend_name = "xla-multihost"

    def __init__(self, client, group_name: str, world_size: int, rank: int,
                 timeout_s: float = 60.0):
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} out of range for world {world_size}")
        self._client = client
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._kv_fallback = None  # lazily built for host-staged p2p
        self._init_jax_cluster(timeout_s)

    # ------------------------------------------------------------ rendezvous
    def _coord_key(self) -> bytes:
        return f"{self.group_name}:coordinator".encode()

    def _init_jax_cluster(self, timeout_s: float) -> None:
        import jax

        # env check ONLY — jax.default_backend() would initialize XLA,
        # which must not happen before jax.distributed.initialize
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            # the reference's mock-NCCL pattern: same code path, CPU gloo
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        if self.rank == 0:
            import socket

            with socket.socket() as s:
                s.bind(("", 0))
                port = s.getsockname()[1]
            host = os.environ.get("RAY_TPU_NODE_IP", "127.0.0.1")
            addr = f"{host}:{port}"
            self._client.kv_put(
                _COORD_NS, self._coord_key(),
                pickle.dumps({"addr": addr, "ts": time.time()}),
                overwrite=True)
        else:
            deadline = time.monotonic() + timeout_s
            while True:
                blob = self._client.kv_get(_COORD_NS, self._coord_key())
                if blob:
                    entry = pickle.loads(blob)
                    # reject leftovers of a crashed same-named group: a
                    # live rendezvous key is at most timeout_s old (rank 0
                    # deletes it once everyone has joined)
                    if time.time() - entry["ts"] <= timeout_s:
                        addr = entry["addr"]
                        break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"group {self.group_name}: no coordinator within "
                        f"{timeout_s}s")
                time.sleep(_POLL_S)
        self._ensure_jax_distributed(addr)
        if self.rank == 0:
            # initialize() returns once every process has joined — the
            # rendezvous key has served its purpose
            try:
                self._client.kv_del(_COORD_NS, self._coord_key())
            except Exception:
                pass
        import jax
        from jax.sharding import Mesh

        per_proc = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        if len(per_proc) != self.world_size:
            raise RuntimeError(
                f"jax cluster has {len(per_proc)} processes, expected "
                f"{self.world_size}")
        devs = [per_proc[i] for i in range(self.world_size)]
        self.mesh = Mesh(np.array(devs), ("p",))
        self._local_dev = per_proc[jax.process_index()]

    def _ensure_jax_distributed(self, addr: str) -> None:
        """Join (or reuse) this process's jax.distributed cluster.

        initialize() is once-per-process; a second group in the same
        process reuses the existing cluster when its geometry matches
        (process count == world_size, our index == rank) and fails loudly
        otherwise — never with jax's opaque 'already initialized' error."""
        import jax
        from jax._src import distributed as jdist

        state = getattr(jdist, "global_state", None)
        if state is not None and state.client is not None:
            if (jax.process_count() != self.world_size
                    or jax.process_index() != self.rank):
                raise RuntimeError(
                    f"group {self.group_name}: this process already belongs "
                    f"to a jax.distributed cluster of "
                    f"{jax.process_count()} processes (as index "
                    f"{jax.process_index()}) — an xla-multihost group must "
                    f"match it (asked world={self.world_size} "
                    f"rank={self.rank})")
            return
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=self.world_size,
                                   process_id=self.rank)

    # ------------------------------------------------------------- data plane
    def _global(self, x: np.ndarray):
        """Local array -> global [world, ...] jax.Array, one shard per
        process, sharded over the mesh's `p` axis."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = np.ascontiguousarray(x)
        sharding = NamedSharding(self.mesh, P("p", *([None] * x.ndim)))
        local = jax.device_put(x[None], self._local_dev)
        return jax.make_array_from_single_device_arrays(
            (self.world_size,) + x.shape, sharding, [local])

    def _shard_map(self, fn, g):
        import jax
        from jax.sharding import PartitionSpec as P

        return jax.shard_map(fn, mesh=self.mesh, in_specs=P("p"),
                             out_specs=P("p"))(g)

    def _local_of(self, garr) -> np.ndarray:
        """This process's shard of a [world, ...] global array."""
        shard = garr.addressable_shards[0]
        return np.asarray(shard.data)[0]

    # ------------------------------------------------------------ collectives
    def _allreduce_np(self, x: np.ndarray, op: ReduceOp) -> np.ndarray:
        red = _reduce_op(op)
        out = self._shard_map(lambda a: red(a, "p"), self._global(x))
        return self._local_of(out)

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM, timeout=None):
        from ray_tpu.util.collective.kv_group import _write_back

        # in-place semantics match the kv/reference backends: the caller's
        # tensor holds the reduced value afterwards
        return _write_back(tensor, self._allreduce_np(np.asarray(tensor), op))

    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM,
               timeout=None):
        from ray_tpu.util.collective.kv_group import _write_back

        out = self._allreduce_np(np.asarray(tensor), op)
        if self.rank == dst_rank:
            return _write_back(tensor, out)
        return tensor

    def broadcast(self, tensor, src_rank: int = 0, timeout=None):
        from ray_tpu.util.collective.kv_group import _write_back

        x = np.asarray(tensor)
        contrib = x if self.rank == src_rank else np.zeros_like(x)
        return _write_back(tensor, self._allreduce_np(contrib, ReduceOp.SUM))

    def allgather(self, tensor, timeout=None) -> List[np.ndarray]:
        from jax import lax

        x = np.asarray(tensor)
        out = self._shard_map(
            lambda a: lax.all_gather(a[0], "p")[None], self._global(x))
        gathered = self._local_of(out)  # [world, ...]
        return [gathered[i] for i in range(self.world_size)]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM, timeout=None):
        """Input [world, ...]; returns this rank's reduced slice."""
        arr = np.asarray(tensor)
        if arr.shape[0] != self.world_size:
            raise ValueError(
                f"reducescatter input leading dim {arr.shape[0]} != world "
                f"{self.world_size}")
        # psum the full [world, ...] then each rank keeps its slice — XLA
        # lowers psum+slice to reduce-scatter on device meshes
        return self._allreduce_np(arr, op)[self.rank]

    def barrier(self, timeout=None):
        from jax.experimental import multihost_utils

        # name must be IDENTICAL on every process (it is hashed and
        # compared); a per-group counter keeps successive barriers distinct
        self._barrier_seq = getattr(self, "_barrier_seq", 0) + 1
        multihost_utils.sync_global_devices(
            f"{self.group_name}:barrier:{self._barrier_seq}")

    # ------------------------------------------------------------------- p2p
    def _fallback(self):
        if self._kv_fallback is None:
            from ray_tpu.util.collective.kv_group import KVCollectiveGroup

            self._kv_fallback = KVCollectiveGroup(
                self._client, f"{self.group_name}:p2p", self.world_size,
                self.rank)
        return self._kv_fallback

    def send(self, tensor, dst_rank: int, timeout=None):
        self._fallback().send(tensor, dst_rank, timeout=timeout)

    def recv(self, tensor, src_rank: int, timeout=None):
        return self._fallback().recv(tensor, src_rank, timeout=timeout)

    def destroy(self):
        if self._kv_fallback is not None:
            self._kv_fallback.destroy()
        if self.rank == 0:
            try:
                self._client.kv_del(_COORD_NS, self._coord_key())
            except Exception:
                pass
