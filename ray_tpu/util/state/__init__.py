"""State API: programmatic cluster introspection.

Parity: `python/ray/util/state/api.py` (`ray list tasks/actors/objects/...`,
summary APIs) backed by the head's live tables instead of a separate
dashboard StateHead process.
"""

from ray_tpu.util.state.api import (get_actor, get_placement_group, list_actors,
                                    subscribe,
                                    list_lease_events, list_nodes,
                                    list_objects,
                                    list_placement_groups,
                                    list_scheduler_stats, list_serve_stats,
                                    list_task_events,
                                    list_tasks, list_trace_spans,
                                    list_workers, list_workload_stats,
                                    summarize_actors,
                                    summarize_objects, summarize_tasks)

__all__ = [
    "subscribe",
    "get_actor", "get_placement_group", "list_actors", "list_lease_events",
    "list_nodes",
    "list_objects", "list_placement_groups", "list_scheduler_stats",
    "list_serve_stats",
    "list_task_events", "list_tasks", "list_trace_spans",
    "list_workers", "list_workload_stats",
    "summarize_actors", "summarize_objects", "summarize_tasks",
]
