"""State API implementation.

Each `list_*` supports the reference's filter grammar subset:
`filters=[("key", "=", value), ("key", "!=", value)]` plus `limit`
(`python/ray/util/state/api.py` list_tasks/list_actors/... semantics).
"""

from __future__ import annotations

from collections import Counter
from typing import Any, List, Optional, Sequence, Tuple


def _client():
    from ray_tpu.core.api import _auto_init, _global_client

    _auto_init()
    return _global_client()


def _apply_filters(rows: List[dict],
                   filters: Optional[Sequence[Tuple[str, str, Any]]],
                   limit: Optional[int]) -> List[dict]:
    if filters:
        for key, op, val in filters:
            if op == "=":
                rows = [r for r in rows if r.get(key) == val]
            elif op == "!=":
                rows = [r for r in rows if r.get(key) != val]
            else:
                raise ValueError(f"unsupported filter op {op!r} (use '=' or '!=')")
    return rows[:limit] if limit else rows


def _list(kind: str, filters=None, limit: Optional[int] = None) -> List[dict]:
    rows = _client().head_request("list_state", kind=kind)
    return _apply_filters(rows, filters, limit)


def list_tasks(filters=None, limit=None) -> List[dict]:
    """Queued (not-yet-dispatched) tasks; completed ones are in
    `list_task_events`."""
    return _list("tasks", filters, limit)


def list_task_events(filters=None, limit=None) -> List[dict]:
    """Task lifecycle transitions (PENDING_* / RUNNING / FINISHED / FAILED)."""
    return _list("task_events", filters, limit)


def list_actors(filters=None, limit=None) -> List[dict]:
    return _list("actors", filters, limit)


def list_workers(filters=None, limit=None) -> List[dict]:
    return _list("workers", filters, limit)


def list_objects(filters=None, limit=None) -> List[dict]:
    return _list("objects", filters, limit)


def list_nodes(filters=None, limit=None) -> List[dict]:
    return _list("nodes", filters, limit)


def list_placement_groups(filters=None, limit=None) -> List[dict]:
    return _list("placement_groups", filters, limit)


def list_lease_events(filters=None, limit=None) -> List[dict]:
    """Flight-recorder lease-lifecycle events merged at the head: each
    node daemon's local grants/spillbacks/pool churn (piggybacked on the
    resource-view gossip, ack-tracked so a dying connection cannot drop a
    drained batch) plus head-granted leases, node deaths, and the
    partition-tolerance protocol (reconciliation handshakes, stale-epoch
    rejections, head reconnects). Row keys: kind (local_grant | spillback
    | pool_acquire | lease_return | pool_release | pool_worker_died |
    view_adopt | head_grant | node_dead | node_reregister |
    pool_reconcile | stale_epoch | head_lost | head_reconnect |
    chaos_config), node_id, ts, and per-kind detail."""
    return _list("lease_events", filters, limit)


def list_scheduler_stats(filters=None, limit=None) -> List[dict]:
    """Per-node two-level-scheduler telemetry: lifetime local-grant /
    spillback counters, warm-pool size (idle_workers / leased_workers as
    gossiped by the daemon vs pooled_workers as carved in the head
    ledger — equal after reconciliation), the reconciliation state
    (reconciled / pending_pool), gossip health (view version, view age)
    and head-observed delta staleness — one row per node daemon plus one
    `is_head` row with the head's grant totals, cluster epoch, and
    stale-epoch reject / reconcile counters."""
    return _list("scheduler_stats", filters, limit)


def list_serve_stats(filters=None, limit=None) -> List[dict]:
    """Live serve load merged at the head from telemetry piggybacked on
    the existing metrics-push/gossip channel (zero new RPCs): one row per
    replica (kind=serve_replica: queue_depth, inflight, ewma_latency_s,
    total) plus any serve-scoped rows other publishers add. Row keys:
    kind, key, stats, ts, proc."""
    return _list("serve_stats", filters, limit)


def list_workload_stats(filters=None, limit=None) -> List[dict]:
    """Every workload telemetry row the head has merged — serve replicas
    AND train workers (kind=train_worker: step, last_step_s,
    ewma_step_s, steps_per_s per rank). Superset of
    `list_serve_stats`."""
    return _list("workload_stats", filters, limit)


def list_trace_spans(filters=None, limit=None) -> List[dict]:
    """Finished spans pushed by every process (workload flight
    recorder), tagged with proc/node — `ray_tpu.timeline(
    format="chrome")` merges them into one cross-process trace. Row
    keys: name, trace_id, span_id, parent_id, start_ts, end_ts,
    attributes, proc, node."""
    return _list("trace_spans", filters, limit)


def get_actor(actor_id: str) -> Optional[dict]:
    rows = list_actors(filters=[("actor_id", "=", actor_id)])
    return rows[0] if rows else None


def get_placement_group(pg_id: str) -> Optional[dict]:
    rows = list_placement_groups(filters=[("pg_id", "=", pg_id)])
    return rows[0] if rows else None


# ------------------------------------------------------------------ summary
# pure row-level helpers shared with the dashboard's /api/summary
def summarize_task_rows(events: List[dict]) -> dict:
    """Latest state per task id, counted (reference `ray summary tasks`)."""
    latest: dict = {}
    for ev in events:
        latest[ev["task_id"]] = ev["state"]
    return {"total": len(latest), "by_state": dict(Counter(latest.values()))}


def summarize_actor_rows(rows: List[dict]) -> dict:
    counts = Counter(a["state"] for a in rows)
    return {"total": sum(counts.values()), "by_state": dict(counts)}


def summarize_object_rows(rows: List[dict]) -> dict:
    return {"total": len(rows),
            "total_size_bytes": sum(r.get("size") or 0 for r in rows),
            "by_kind": dict(Counter(r["kind"] for r in rows))}


def summarize_tasks() -> dict:
    return summarize_task_rows(_list("task_events"))


def summarize_actors() -> dict:
    return summarize_actor_rows(_list("actors"))


def summarize_objects() -> dict:
    return summarize_object_rows(_list("objects"))


def subscribe(channel: str):
    """Subscribe to a head pubsub channel; returns a queue.Queue of event
    dicts (reference pubsub channels: node/actor/object state). Usage:

        q = state.subscribe("object_state")
        evt = q.get(timeout=5)   # {"object_id": ..., "state": "SEALED"}
    """
    import queue as _q

    from ray_tpu.core.api import _global_client

    out: "_q.Queue" = _q.Queue()
    _global_client().subscribe_channel(channel, out.put)
    return out
