"""joblib backend running jobs as cluster tasks.

Capability-equivalent of the reference's `ray.util.joblib`
(`python/ray/util/joblib/__init__.py` + `ray_backend.py`): register a
parallel backend named "ray_tpu" so `joblib.Parallel` (and scikit-learn's
`with parallel_backend(...)`) fans out across the cluster.
"""

from __future__ import annotations

import ray_tpu

_registered = False


def register_ray() -> None:
    """Register the "ray_tpu" joblib backend (idempotent)."""
    global _registered
    if _registered:
        return
    from joblib.parallel import register_parallel_backend
    register_parallel_backend("ray_tpu", _RayTpuBackend)
    _registered = True


def _make_backend():
    from joblib._parallel_backends import MultiprocessingBackend

    class _RayTpuBackend(MultiprocessingBackend):
        """joblib backend whose pool is ray_tpu.util.multiprocessing.Pool."""

        supports_timeout = True

        def effective_n_jobs(self, n_jobs):
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            eff = int(ray_tpu.cluster_resources().get("CPU", 1))
            if n_jobs and n_jobs > 0:
                eff = min(eff, n_jobs)
            return max(1, eff)

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **kwargs):
            n_jobs = self.effective_n_jobs(n_jobs)
            from ray_tpu.util.multiprocessing import Pool
            self._pool = Pool(processes=n_jobs)
            self.parallel = parallel
            return n_jobs

        def terminate(self):
            if getattr(self, "_pool", None) is not None:
                self._pool.terminate()
                self._pool = None

    return _RayTpuBackend


class _LazyBackend:
    """Defer the joblib import until the backend is actually constructed."""

    _cls = None

    def __new__(cls, *args, **kwargs):
        if cls._cls is None:
            cls._cls = _make_backend()
        return cls._cls(*args, **kwargs)


_RayTpuBackend = _LazyBackend
