"""Drop-in `multiprocessing.Pool` built on tasks/actors.

Capability-equivalent of the reference's `ray.util.multiprocessing.Pool`
(`python/ray/util/multiprocessing/pool.py`): a process pool whose workers are
cluster actors, with the stdlib Pool surface (apply/apply_async, map/map_async,
starmap, imap, imap_unordered, close/terminate/join) so existing
multiprocessing code scales across nodes unchanged.
"""

from __future__ import annotations

import itertools
import multiprocessing
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu
from ray_tpu.core.exceptions import GetTimeoutError


@ray_tpu.remote
class _PoolActor:
    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_batch(self, fn, batch, star):
        if star:
            return [fn(*args) for args in batch]
        return [fn(args) for args in batch]

    def run_apply(self, fn, args, kwargs):
        return fn(*args, **kwargs)


class AsyncResult:
    """Matches `multiprocessing.pool.AsyncResult`."""

    def __init__(self, refs: List[Any], single: bool, chunked: bool,
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._refs = refs
        self._single = single
        self._chunked = chunked
        if callback is not None or error_callback is not None:
            import threading

            def watch():
                try:
                    result = self.get()
                except Exception as e:
                    if error_callback is not None:
                        error_callback(e)
                else:
                    if callback is not None:
                        callback(result)

            threading.Thread(target=watch, daemon=True).start()

    def get(self, timeout: Optional[float] = None):
        try:
            out = ray_tpu.get(self._refs, timeout=timeout)
        except GetTimeoutError:
            # stdlib contract: multiprocessing.TimeoutError (ProcessError
            # subclass), which is what drop-in callers catch
            raise multiprocessing.TimeoutError()
        if self._chunked:
            out = list(itertools.chain.from_iterable(out))
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            # stdlib contract: ValueError before completion, never block
            raise ValueError("AsyncResult is not ready")
        try:
            ray_tpu.get(self._refs)
            return True
        except Exception:
            return False


class Pool:
    """Example:
        with Pool(processes=4) as p:
            assert p.map(abs, [-1, -2]) == [1, 2]
    """

    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs=(), maxtasksperchild: Optional[int] = None,
                 ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._processes = processes
        opts = dict(ray_remote_args or {})
        opts.setdefault("num_cpus", 1)
        self._opts = opts
        self._init = (initializer, tuple(initargs))
        self._maxtasksperchild = maxtasksperchild
        self._actors = [self._spawn_actor() for _ in range(processes)]
        self._task_counts = [0] * processes
        self._next_idx = 0
        self._inflight: List[Any] = []
        self._closed = False

    def _spawn_actor(self):
        return _PoolActor.options(**self._opts).remote(*self._init)

    def _next_actor(self):
        """Round-robin with maxtasksperchild recycling (stdlib semantics:
        a worker is replaced after executing that many tasks)."""
        i = self._next_idx
        self._next_idx = (self._next_idx + 1) % self._processes
        if (self._maxtasksperchild is not None
                and self._task_counts[i] >= self._maxtasksperchild):
            ray_tpu.kill(self._actors[i])
            self._actors[i] = self._spawn_actor()
            self._task_counts[i] = 0
        self._task_counts[i] += 1
        return self._actors[i]

    def _track(self, refs):
        # A single batched wait() per submission — per-ref wait calls were
        # O(inflight) control-plane round trips (quadratic over a large
        # map()), while deferring pruning would pin completed results.
        if self._inflight:
            _, pending = ray_tpu.wait(self._inflight,
                                      num_returns=len(self._inflight),
                                      timeout=0)
            self._inflight = list(pending)
        self._inflight.extend(refs)
        return refs

    # -------------------------------------------------------------- apply
    def apply(self, func: Callable, args=(), kwds=None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args=(), kwds=None,
                    callback: Optional[Callable] = None,
                    error_callback: Optional[Callable] = None) -> AsyncResult:
        self._check_running()
        actor = self._next_actor()
        ref = actor.run_apply.remote(func, tuple(args), kwds or {})
        self._track([ref])
        return AsyncResult([ref], single=True, chunked=False,
                           callback=callback, error_callback=error_callback)

    # ---------------------------------------------------------------- map
    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize] for i in range(0, len(items), chunksize)]

    def _map_refs(self, func, iterable, chunksize, star):
        self._check_running()
        refs = []
        for batch in self._chunks(iterable, chunksize):
            refs.append(self._next_actor().run_batch.remote(func, batch, star))
        return self._track(refs)

    def map(self, func: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return AsyncResult(self._map_refs(func, iterable, chunksize, False),
                           single=False, chunked=True).get()

    def map_async(self, func, iterable, chunksize=None) -> AsyncResult:
        return AsyncResult(self._map_refs(func, iterable, chunksize, False),
                           single=False, chunked=True)

    def starmap(self, func, iterable, chunksize=None) -> List[Any]:
        return AsyncResult(self._map_refs(func, iterable, chunksize, True),
                           single=False, chunked=True).get()

    def starmap_async(self, func, iterable, chunksize=None) -> AsyncResult:
        return AsyncResult(self._map_refs(func, iterable, chunksize, True),
                           single=False, chunked=True)

    def imap(self, func, iterable, chunksize: int = 1):
        refs = self._map_refs(func, iterable, chunksize, False)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, func, iterable, chunksize: int = 1):
        refs = self._map_refs(func, iterable, chunksize, False)
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(ready[0])

    # -------------------------------------------------------------- admin
    def _check_running(self):
        if self._closed:
            raise ValueError("Pool not running")

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True
        for a in self._actors:
            ray_tpu.kill(a)

    def join(self) -> None:
        """Blocks until every task submitted before close() finishes
        (stdlib contract), so terminate()/__exit__ cannot kill mid-task."""
        if not self._closed:
            raise ValueError("Pool is still running")
        if self._inflight:
            ray_tpu.wait(self._inflight, num_returns=len(self._inflight))
            self._inflight = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
