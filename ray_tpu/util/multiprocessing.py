"""Drop-in `multiprocessing.Pool` built on tasks/actors.

Capability-equivalent of the reference's `ray.util.multiprocessing.Pool`
(`python/ray/util/multiprocessing/pool.py`): a process pool whose workers are
cluster actors, with the stdlib Pool surface (apply/apply_async, map/map_async,
starmap, imap, imap_unordered, close/terminate/join) so existing
multiprocessing code scales across nodes unchanged.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool


@ray_tpu.remote
class _PoolActor:
    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_batch(self, fn, batch, star):
        if star:
            return [fn(*args) for args in batch]
        return [fn(args) for args in batch]

    def run_apply(self, fn, args, kwargs):
        return fn(*args, **kwargs)


class AsyncResult:
    """Matches `multiprocessing.pool.AsyncResult`."""

    def __init__(self, refs: List[Any], single: bool, chunked: bool,
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._refs = refs
        self._single = single
        self._chunked = chunked
        if callback is not None or error_callback is not None:
            import threading

            def watch():
                try:
                    result = self.get()
                except Exception as e:
                    if error_callback is not None:
                        error_callback(e)
                else:
                    if callback is not None:
                        callback(result)

            threading.Thread(target=watch, daemon=True).start()

    def get(self, timeout: Optional[float] = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        if self._chunked:
            out = list(itertools.chain.from_iterable(out))
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            ray_tpu.get(self._refs)
            return True
        except Exception:
            return False


class Pool:
    """Example:
        with Pool(processes=4) as p:
            assert p.map(abs, [-1, -2]) == [1, 2]
    """

    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs=(), maxtasksperchild: Optional[int] = None,
                 ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._processes = processes
        opts = dict(ray_remote_args or {})
        opts.setdefault("num_cpus", 1)
        self._actors = [
            _PoolActor.options(**opts).remote(initializer, tuple(initargs))
            for _ in range(processes)
        ]
        self._pool = ActorPool(self._actors)
        self._rr = itertools.cycle(self._actors)
        self._closed = False

    # -------------------------------------------------------------- apply
    def apply(self, func: Callable, args=(), kwds=None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args=(), kwds=None,
                    callback: Optional[Callable] = None,
                    error_callback: Optional[Callable] = None) -> AsyncResult:
        self._check_running()
        actor = next(self._rr)
        ref = actor.run_apply.remote(func, tuple(args), kwds or {})
        return AsyncResult([ref], single=True, chunked=False,
                           callback=callback, error_callback=error_callback)

    # ---------------------------------------------------------------- map
    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize] for i in range(0, len(items), chunksize)]

    def _map_refs(self, func, iterable, chunksize, star):
        self._check_running()
        refs = []
        actors = itertools.cycle(self._actors)
        for batch in self._chunks(iterable, chunksize):
            refs.append(next(actors).run_batch.remote(func, batch, star))
        return refs

    def map(self, func: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return AsyncResult(self._map_refs(func, iterable, chunksize, False),
                           single=False, chunked=True).get()

    def map_async(self, func, iterable, chunksize=None) -> AsyncResult:
        return AsyncResult(self._map_refs(func, iterable, chunksize, False),
                           single=False, chunked=True)

    def starmap(self, func, iterable, chunksize=None) -> List[Any]:
        return AsyncResult(self._map_refs(func, iterable, chunksize, True),
                           single=False, chunked=True).get()

    def starmap_async(self, func, iterable, chunksize=None) -> AsyncResult:
        return AsyncResult(self._map_refs(func, iterable, chunksize, True),
                           single=False, chunked=True)

    def imap(self, func, iterable, chunksize: int = 1):
        refs = self._map_refs(func, iterable, chunksize, False)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, func, iterable, chunksize: int = 1):
        refs = self._map_refs(func, iterable, chunksize, False)
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(ready[0])

    # -------------------------------------------------------------- admin
    def _check_running(self):
        if self._closed:
            raise ValueError("Pool not running")

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True
        for a in self._actors:
            ray_tpu.kill(a)

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
