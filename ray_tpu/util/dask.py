"""Dask-on-ray_tpu scheduler shim.

Parity: `python/ray/util/dask/` (`ray_dask_get`) — execute a dask task
graph with ray_tpu tasks as the execution engine, so
`dask.compute(..., scheduler=ray_dask_get)` fans the graph's independent
tasks over the cluster.

Dask graphs are plain dicts `{key: spec}` where a spec is a computable
task `(callable, arg...)`, a key reference, or a literal — we walk that
protocol directly, so the shim also works on hand-built graphs with no
dask installed (dask itself is only needed for `dask.compute`)."""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef


def _is_task(spec: Any) -> bool:
    return isinstance(spec, tuple) and spec and callable(spec[0])


def _toposort(dsk: Dict[Hashable, Any]) -> List[Hashable]:
    seen: Dict[Hashable, int] = {}   # 0=visiting, 1=done
    order: List[Hashable] = []

    def deps(spec):
        if _is_task(spec):
            for a in spec[1:]:
                yield from deps(a)
        elif isinstance(spec, list):
            for a in spec:
                yield from deps(a)
        elif isinstance(spec, Hashable) and spec in dsk:
            yield spec

    def visit(key):
        st = seen.get(key)
        if st == 1:
            return
        if st == 0:
            raise ValueError(f"cycle in dask graph at {key!r}")
        seen[key] = 0
        for d in deps(dsk[key]):
            visit(d)
        seen[key] = 1
        order.append(key)

    for k in dsk:
        visit(k)
    return order


@ray_tpu.remote
def _run_spec(fn, *args):
    # top-level ObjectRef args resolve before invocation (normal task
    # semantics); dask also nests key refs inside LISTS ((sum, [a, b]))
    # which arrive as ObjectRefs — materialize those here
    def mat(a):
        if isinstance(a, list):
            return [mat(x) for x in a]
        if isinstance(a, ObjectRef):
            return ray_tpu.get(a)
        return a

    return fn(*[mat(a) for a in args])


def ray_dask_get(dsk: Dict[Hashable, Any], keys, **_kwargs):
    """Dask scheduler entry point: materialize `keys` from graph `dsk`.
    Independent tasks run as concurrent ray_tpu tasks; dependencies ride
    as ObjectRefs (never gathered onto the driver mid-graph)."""
    refs: Dict[Hashable, Any] = {}

    def resolve(spec):
        """spec -> (value-or-ref, is_ref)."""
        if _is_task(spec):
            fn = spec[0]
            args = [resolve(a) for a in spec[1:]]
            return _run_spec.remote(fn, *args)
        if isinstance(spec, list):
            return [resolve(a) for a in spec]
        if isinstance(spec, Hashable) and spec in refs:
            return refs[spec]
        return spec

    for key in _toposort(dsk):
        spec = dsk[key]
        if _is_task(spec):
            refs[key] = resolve(spec)
        elif isinstance(spec, Hashable) and spec in refs:
            refs[key] = refs[spec]
        else:
            refs[key] = spec

    def gather(k):
        if isinstance(k, list):
            return [gather(x) for x in k]
        v = refs[k]
        return ray_tpu.get(v) if isinstance(v, ObjectRef) else v

    return gather(list(keys) if isinstance(keys, (list, tuple)) else keys)
