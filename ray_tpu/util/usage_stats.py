"""Usage-stats telemetry (opt-in here; the reference is opt-out).

Parity: `python/ray/_common/usage/usage_lib.py` — a periodic ping with
cluster metadata and library-usage tags. This build runs in egress-less
environments, so the transport is pluggable: the default reporter writes
JSON lines under the session dir (operators ship them however they like);
a custom reporter callable can POST wherever. Controlled by the
`usage_stats` config flag (RAY_TPU_USAGE_STATS; default off).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Callable, Dict, Optional, Set

from ray_tpu.utils.platform import STATE_DIR

_lock = threading.Lock()
_library_usages: Set[str] = set()
_extra_tags: Dict[str, str] = {}
_thread: Optional[threading.Thread] = None
_stop = threading.Event()


def record_library_usage(name: str) -> None:
    """Called by Train/Tune/Serve/Data/RLlib entry points (reference
    `record_library_usage`): which libraries a cluster actually used."""
    with _lock:
        _library_usages.add(name)


def record_extra_usage_tag(key: str, value: str) -> None:
    with _lock:
        _extra_tags[key] = str(value)


def usage_stats_enabled() -> bool:
    from ray_tpu.core import config as _config

    return bool(_config.get("usage_stats"))


def _collect(session: str) -> dict:
    import ray_tpu

    try:
        from ray_tpu.core.api import _global_client

        client = _global_client()
        info = client.head_request("cluster_info") if client else {}
    except Exception:
        info = {}
    with _lock:
        libs = sorted(_library_usages)
        tags = dict(_extra_tags)
    return {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "session_id": session,
        "timestamp": int(time.time()),
        "python_version": sys.version.split()[0],
        "version": getattr(ray_tpu, "__version__", "0.0.0"),
        "os": sys.platform,
        "total_num_nodes": info.get("num_nodes"),
        "total_resources": info.get("total_resources"),
        "library_usages": libs,
        "extra_usage_tags": tags,
    }


def default_reporter(payload: dict) -> None:
    """Egress-less default: append a JSON line under the session dir."""
    path = os.path.join(STATE_DIR, "usage_stats.jsonl")
    os.makedirs(STATE_DIR, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(payload) + "\n")


def start_usage_stats_heartbeat(
        session: str, interval_s: float = 300.0,
        reporter: Optional[Callable[[dict], None]] = None) -> bool:
    """Begin periodic reporting if enabled. Returns whether it started."""
    global _thread
    if not usage_stats_enabled() or _thread is not None:
        return False
    reporter = reporter or default_reporter
    _stop.clear()

    def loop():
        while not _stop.is_set():
            try:
                reporter(_collect(session))
            except Exception:
                pass  # telemetry must never break the cluster
            _stop.wait(interval_s)

    _thread = threading.Thread(target=loop, daemon=True,
                               name="usage-stats")
    _thread.start()
    return True


def stop_usage_stats_heartbeat() -> None:
    global _thread
    _stop.set()
    if _thread is not None:
        _thread.join(timeout=2)
        _thread = None
