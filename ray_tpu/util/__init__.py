"""Parity namespace for the reference's `ray.util` surface."""

from ray_tpu.core.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)

__all__ = ["PlacementGroup", "placement_group", "remove_placement_group"]
