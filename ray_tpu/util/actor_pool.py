"""Actor pool utility: round-robin work distribution over a fixed set of actors.

Capability-equivalent of the reference's `ray.util.actor_pool.ActorPool`
(`python/ray/util/actor_pool.py`): submit/map work onto idle actors, consume
results in submission or completion order, grow/shrink the pool.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    """Pool of actor handles with map/submit semantics.

    Example:
        pool = ActorPool([Worker.remote() for _ in range(4)])
        results = list(pool.map(lambda a, v: a.double.remote(v), range(100)))
    """

    def __init__(self, actors: Iterable[Any]):
        self._idle_actors: List[Any] = list(actors)
        # in-flight: ObjectRef -> actor that produced it
        self._future_to_actor = {}
        # ordering for get_next(): index -> ref (+ reverse for O(1)
        # removal from get_next_unordered)
        self._index_to_future = {}
        self._future_to_index = {}
        self._next_task_index = 0
        self._next_return_index = 0
        # tasks buffered while no actor is free
        self._pending_submits = []

    # ------------------------------------------------------------- submit
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """Schedule fn(actor, value) on an idle actor (or buffer it)."""
        if self._idle_actors:
            actor = self._idle_actors.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = actor
            self._index_to_future[self._next_task_index] = future
            self._future_to_index[future] = self._next_task_index
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def has_free(self) -> bool:
        return bool(self._idle_actors) and not self._pending_submits

    def _return_actor(self, actor) -> None:
        self._idle_actors.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    # --------------------------------------------------------------- next
    def get_next(self, timeout: float | None = None, ignore_if_timedout: bool = False):
        """Next result in submission order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        future = self._index_to_future[self._next_return_index]
        if timeout is not None:
            done = ray_tpu.wait([future], num_returns=1, timeout=timeout)[0]
            if not done:
                if ignore_if_timedout:
                    return None
                raise TimeoutError(f"no result within {timeout}s")
        future = self._index_to_future.pop(self._next_return_index)
        self._future_to_index.pop(future, None)
        self._next_return_index += 1
        actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        return ray_tpu.get(future)

    def get_next_unordered(self, timeout: float | None = None):
        """Next result in completion order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor), num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError(f"no result within {timeout}s")
        future = ready[0]
        actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        self._index_to_future.pop(self._future_to_index.pop(future), None)
        return ray_tpu.get(future)

    # ---------------------------------------------------------------- map
    def map(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]):
        """Lazy iterator of results in submission order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]):
        """Lazy iterator of results in completion order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # --------------------------------------------------------- pool admin
    def push(self, actor) -> None:
        """Add an idle actor to the pool."""
        busy = set(self._future_to_actor.values())
        if actor in self._idle_actors or actor in busy:
            raise ValueError("actor already in pool")
        self._return_actor(actor)

    def pop_idle(self):
        """Remove and return an idle actor, or None if all are busy."""
        if self._idle_actors:
            return self._idle_actors.pop()
        return None
