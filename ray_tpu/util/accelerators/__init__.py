from ray_tpu.util.accelerators.tpu import (  # noqa: F401
    SliceReservation, release_tpu_slice, reserve_tpu_slice)
