"""TPU slice gang scheduling helpers.

Behavioral parity with the reference's multi-host TPU flow
(`python/ray/_private/accelerators/tpu.py:145 reserve_tpu_slice`, `:131
fetch_tpu_slice_name_from_pg`, used by Train v2 at SURVEY §3.4): reserve the
slice via a placement group on the per-slice `TPU-{pod}-head` resource, then
read the slice name off the reserved node so workers can gang-place onto all
hosts of that slice with the `ray.io/tpu-slice-name` label selector.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ray_tpu.core.placement_group import PlacementGroup, placement_group

SLICE_NAME_LABEL = "ray.io/tpu-slice-name"
WORKER_ID_LABEL = "ray.io/tpu-worker-id"
POD_TYPE_LABEL = "ray.io/tpu-pod-type"
TOPOLOGY_LABEL = "ray.io/tpu-topology"


@dataclasses.dataclass
class SliceReservation:
    """A claimed multi-host slice: placement group pinning its head + the
    slice name every worker of the slice is labeled with."""

    pod_type: str
    slice_name: str
    pg: PlacementGroup

    @property
    def label_selector(self) -> dict:
        return {SLICE_NAME_LABEL: self.slice_name}


def slice_head_resource(pod_type: str) -> str:
    return f"TPU-{pod_type}-head"


def num_hosts_for_pod(pod_type: str) -> int:
    """v5e-16 -> 16 chips -> 4 hosts (4 chips/host), mirroring the
    reference's pod-name arithmetic (tpu.py GKE metadata path)."""
    try:
        chips = int(pod_type.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return 1
    return max(1, chips // 4)


def reserve_tpu_slice(pod_type: str, timeout: Optional[float] = 60,
                      ) -> SliceReservation:
    """Claim one whole slice of `pod_type` and learn its name.

    Creates a PG on the slice-head resource (only worker 0 of each slice
    advertises it), then runs a probe task inside the PG to read the slice
    name from that node's environment."""
    import ray_tpu

    pg = placement_group([{slice_head_resource(pod_type): 1}],
                         strategy="STRICT_PACK",
                         name=f"tpu-slice-{pod_type}")
    if not pg.ready(timeout=timeout):
        from ray_tpu.core.placement_group import remove_placement_group

        remove_placement_group(pg)
        raise TimeoutError(
            f"no free {pod_type} slice (resource "
            f"{slice_head_resource(pod_type)!r} unavailable)")

    @ray_tpu.remote
    def _fetch_slice_name():
        from ray_tpu.core.resources import tpu_slice_name

        return tpu_slice_name()

    name = ray_tpu.get(
        _fetch_slice_name.options(num_cpus=0, placement_group=pg).remote(),
        timeout=timeout)
    if name is None:
        name = f"slice-{pg.id.hex()[:8]}"
    return SliceReservation(pod_type=pod_type, slice_name=name, pg=pg)


def release_tpu_slice(reservation: SliceReservation) -> None:
    from ray_tpu.core.placement_group import remove_placement_group

    remove_placement_group(reservation.pg)
