"""Metrics API: Counter / Gauge / Histogram with Prometheus export.

Parity: `ray.util.metrics` (`python/ray/util/metrics.py` → Cython
`includes/metric.pxi` → per-node agent → Prometheus). Here every process
keeps a local registry and a background thread pushes snapshots into the
head's KV (`_metrics` namespace, one key per process); the dashboard's
`/metrics` endpoint aggregates all snapshots into Prometheus text
exposition format.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_REGISTRY: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], "Metric"] = {}
_LOCK = threading.Lock()
def _PUSH_INTERVAL_S() -> float:
    from ray_tpu.core import config as _config

    return _config.get("metrics_push_interval_s")
_pusher: Optional[threading.Thread] = None
_pusher_stop = threading.Event()
_pusher_enabled = True

# sub-millisecond leading buckets: warm-path RPCs and span latencies sit
# well under 1 ms on localhost — without them every warm observation
# landed in one bucket and p50/p99 were indistinguishable
DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.0001, 0.00025, 0.0005,
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0]

# process-local live-load telemetry (serve replicas: queue depth /
# in-flight / EWMA latency; train workers: step time / throughput).
# Rides the SAME channel as metric snapshots — the pusher for processes
# with a CoreClient, resource_view_delta gossip for node daemons — so
# live load reaches the head with zero new RPC channels.
_WORKLOADS: Dict[Tuple[str, str], dict] = {}


class Metric:
    """Base: a named metric with fixed tag keys; `.set_default_tags` then
    record with per-call tag values (reference API shape)."""

    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        if not name or not name.replace("_", "a").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        # (sorted tag-value tuple) -> value
        self._series: Dict[Tuple[Tuple[str, str], ...], float] = {}
        with _LOCK:
            _REGISTRY[(name, self.tag_keys)] = self
        _ensure_pusher()

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]):
        merged = {**self._default_tags, **(tags or {})}
        unknown = set(merged) - set(self.tag_keys)
        if unknown:
            raise ValueError(f"unknown tag(s) {unknown} for metric {self.name}")
        return tuple(sorted(merged.items()))

    def _snapshot(self) -> List[dict]:
        with _LOCK:
            return [{"tags": dict(k), "value": v}
                    for k, v in self._series.items()]


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        k = self._key(tags)
        with _LOCK:
            self._series[k] = self._series.get(k, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with _LOCK:
            self._series[self._key(tags)] = float(value)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or DEFAULT_HISTOGRAM_BOUNDARIES)
        # series value: {"buckets": [...], "sum": s, "count": n}
        self._hseries: Dict[Tuple, dict] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with _LOCK:
            h = self._hseries.setdefault(
                k, {"buckets": [0] * (len(self.boundaries) + 1),
                    "sum": 0.0, "count": 0})
            i = 0
            while i < len(self.boundaries) and value > self.boundaries[i]:
                i += 1
            h["buckets"][i] += 1
            h["sum"] += value
            h["count"] += 1

    def _snapshot(self) -> List[dict]:
        with _LOCK:
            return [{"tags": dict(k), "histogram": dict(v),
                     "boundaries": list(self.boundaries)}
                    for k, v in self._hseries.items()]


# ------------------------------------------------------ workload telemetry
def publish_workload(kind: str, key: str, stats: Dict[str, object]) -> None:
    """Publish one workload's live-load dict (e.g. kind="serve_replica",
    key=replica_tag). Overwrites the previous value — this is a gauge-like
    snapshot, not an event stream; the head merges the latest copy into
    `state.list_serve_stats()` / `GET /api/workloads`."""
    with _LOCK:
        _WORKLOADS[(kind, key)] = {"kind": kind, "key": key,
                                   "stats": dict(stats), "ts": time.time()}


def workload_snapshot() -> List[dict]:
    with _LOCK:
        return [dict(v) for v in _WORKLOADS.values()]


# ------------------------------------------------------------------ export
def snapshot_all() -> List[dict]:
    with _LOCK:
        metrics = list(_REGISTRY.values())
    return [{"name": m.name, "kind": m.kind, "description": m.description,
             "series": m._snapshot()} for m in metrics]


def push_payload(spans: Optional[List[dict]] = None) -> List[dict]:
    """Full telemetry payload for one push/gossip tick: the metric
    registry snapshot plus two reserved families (names starting "__",
    skipped by the Prometheus renderer) — live workload stats and the
    finished-span batch for the head's cross-process trace buffer.

    Callers that can detect a failed send should drain spans themselves
    and pass them in, so they can `tracing.requeue_push_spans` on
    failure instead of silently losing the batch."""
    payload = snapshot_all()
    wl = workload_snapshot()
    if wl:
        payload.append({"name": "__workloads__", "kind": "workload",
                        "description": "", "series": wl})
    if spans is None:
        from ray_tpu.util import tracing

        spans = tracing.drain_push_spans()
    if spans:
        payload.append({"name": "__spans__", "kind": "spans",
                        "description": "", "series": spans})
    return payload


def _push_once(wait: bool = False) -> bool:
    from ray_tpu.core import api as core_api

    if not core_api.is_initialized():
        return False
    client = core_api._global_client()
    try:
        # a push, not a round trip: snapshots are telemetry and must never
        # add head RPCs to otherwise head-free paths (the warm-path
        # zero-head-RPC contract counts requests, not pushes). The head
        # stores it under the _metrics KV namespace keyed by this
        # process's worker id and expires it on disconnect. Fire-and-forget
        # loses the old round trip's failure signal, so surface the one
        # observable failure mode — a dead head connection — explicitly.
        conn = getattr(client, "conn", None)
        if conn is None or conn.closed:
            return False
    except Exception:
        return False
    from ray_tpu.util import tracing

    spans = tracing.drain_push_spans()
    try:
        value = json.dumps(push_payload(spans)).encode()
        if wait:
            # final flush before the connection closes: a push written
            # just before close can die to a TCP RST (an unread inbound
            # broadcast in our receive buffer at close() turns the FIN
            # into RST, and the head discards undelivered frames) — one
            # shutdown-time round trip guarantees the head PROCESSED the
            # last snapshot/spans before we hang up. Bounded: a head
            # that is ALREADY gone must not stall shutdown behind the
            # reconnect window.
            import asyncio as _asyncio

            fut = _asyncio.run_coroutine_threadsafe(
                conn.request("metrics_push", value=value), client.loop)
            try:
                fut.result(timeout=5)
            except BaseException:
                fut.cancel()
                raise
        else:
            client.head_push("metrics_push", value=value)
        return True
    except Exception:
        # transient head outage: the batch rides the next push instead
        # of silently holing the cross-process timeline
        tracing.requeue_push_spans(spans)
        return False


def disable_pusher() -> None:
    """Processes with no CoreClient (head, node daemons) never have
    anything the pusher could deliver — let them opt out so Metric
    creation doesn't spawn a thread that wakes forever for nothing.
    Daemon registries reach the head by riding gossip instead."""
    global _pusher_enabled
    _pusher_enabled = False


def _ensure_pusher() -> None:
    global _pusher, _pusher_stop
    with _LOCK:
        if _pusher is not None or not _pusher_enabled:
            return
        # per-generation stop event, captured by the thread's closure: a
        # stale thread that outlives its join timeout keeps watching ITS
        # OWN (set) event and exits, regardless of later generations
        stop = _pusher_stop = threading.Event()

        def loop():
            while not stop.wait(_PUSH_INTERVAL_S()):
                _push_once()

        _pusher = threading.Thread(target=loop, daemon=True,
                                   name="metrics-pusher")
        _pusher.start()


def stop_pusher() -> None:
    """Stop the background pusher thread (called by `ray_tpu.shutdown()`);
    the next Metric creation restarts it."""
    global _pusher
    with _LOCK:
        thread, _pusher = _pusher, None
        _pusher_stop.set()
    if thread is not None:
        thread.join(timeout=2)


def flush(wait: bool = False) -> bool:
    """Push this process's metrics to the head immediately. `wait=True`
    turns it into a round trip (used once at shutdown so the final
    snapshot provably lands before the connection closes)."""
    return _push_once(wait=wait)


# -------------------------------------------------- Prometheus text format
def _esc_label(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_tags(tags: Dict[str, str], extra: Optional[Dict[str, str]] = None) -> str:
    merged = {**tags, **(extra or {})}
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_esc_label(v)}"' for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def render_prometheus(snapshots: Dict[str, List[dict]]) -> str:
    """snapshots: {process_key: snapshot_all() output} → exposition text.

    Prometheus exposition requires every sample of a metric family to sit
    under a single `# TYPE` block, so samples are grouped by metric name
    across processes first (per-process iteration would interleave
    families and make strict parsers drop samples)."""
    # name -> {"kind", "description", "samples": [(proc, series_dict)]}
    families: Dict[str, dict] = {}
    for proc, metrics in sorted(snapshots.items()):
        for m in metrics:
            if m["name"].startswith("__"):
                continue  # reserved piggyback families (workloads, spans)
            fam = families.setdefault(
                m["name"], {"kind": m["kind"],
                            "description": m["description"], "samples": []})
            for s in m["series"]:
                fam["samples"].append((proc, s))
    out: List[str] = []
    for mname in sorted(families):
        fam = families[mname]
        name = f"ray_tpu_{mname}"
        desc = str(fam["description"]).replace("\\", "\\\\").replace(
            "\n", "\\n")
        out.append(f"# HELP {name} {desc}")
        out.append(f"# TYPE {name} {fam['kind']}")
        for proc, s in fam["samples"]:
            tags = {**s["tags"], "proc": proc}
            if "histogram" in s:
                h, bounds = s["histogram"], s["boundaries"]
                acc = 0
                for b, c in zip(bounds + [float("inf")], h["buckets"]):
                    acc += c
                    le = "+Inf" if b == float("inf") else repr(b)
                    out.append(f"{name}_bucket"
                               f"{_fmt_tags(tags, {'le': le})} {acc}")
                out.append(f"{name}_sum{_fmt_tags(tags)} {h['sum']}")
                out.append(f"{name}_count{_fmt_tags(tags)} {h['count']}")
            else:
                out.append(f"{name}{_fmt_tags(tags)} {s['value']}")
    return "\n".join(out) + "\n"
