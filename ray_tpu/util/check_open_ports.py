"""Security helper: check which cluster ports are exposed beyond localhost.

Parity: `ray.util.check_open_ports` — enumerate this framework's listening
ports and flag any bound to non-loopback interfaces (a cluster's control
plane should not be internet-reachable).
"""

from __future__ import annotations

import socket
from typing import Dict, List, Optional


def _listening_sockets() -> List[dict]:
    """Parse /proc/net/tcp{,6} for LISTEN sockets of this machine."""
    out = []
    for path, family in (("/proc/net/tcp", socket.AF_INET),
                         ("/proc/net/tcp6", socket.AF_INET6)):
        try:
            with open(path) as f:
                lines = f.readlines()[1:]
        except OSError:
            continue
        for line in lines:
            parts = line.split()
            if len(parts) < 4 or parts[3] != "0A":  # 0A = LISTEN
                continue
            addr_hex, port_hex = parts[1].rsplit(":", 1)
            port = int(port_hex, 16)
            if family == socket.AF_INET:
                raw = bytes.fromhex(addr_hex)[::-1]
                host = socket.inet_ntop(family, raw)
            else:
                raw = bytes.fromhex(addr_hex)
                # /proc stores IPv6 as 4 little-endian 32-bit words
                raw = b"".join(raw[i:i + 4][::-1] for i in range(0, 16, 4))
                host = socket.inet_ntop(family, raw)
            out.append({"host": host, "port": port})
    return out


def check_open_ports(ports: Optional[List[int]] = None) -> Dict[str, list]:
    """Report cluster ports listening on non-loopback addresses.

    With `ports=None`, checks the connected cluster's known ports (head RPC
    + dashboard). Returns {"open_to_network": [...], "loopback_only": [...]}.
    """
    if ports is None:
        ports = []
        try:
            from ray_tpu.core.api import _global_client

            client = _global_client()
            ports.append(client.head_port)
            info = client.head_request("cluster_info")
            if info.get("dashboard_port"):
                ports.append(info["dashboard_port"])
        except Exception as e:
            # an empty report must not read as "all clear" when nothing
            # was actually checked
            raise RuntimeError(
                "could not determine cluster ports (is a cluster "
                f"connected?): {e!r}; pass ports=[...] explicitly") from e
    listening = _listening_sockets()
    loopback = {"127.0.0.1", "::1", "::ffff:127.0.0.1"}
    open_net, loop_only = [], []
    for port in ports:
        socks = [s for s in listening if s["port"] == port]
        exposed = [s for s in socks if s["host"] not in loopback]
        if exposed:
            open_net.append({"port": port, "interfaces": [s["host"] for s in exposed]})
        elif socks:
            loop_only.append(port)
    return {"open_to_network": open_net, "loopback_only": loop_only}
