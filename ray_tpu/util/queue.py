"""Distributed FIFO queue backed by an async actor.

Capability-equivalent of the reference's `ray.util.queue.Queue`
(`python/ray/util/queue.py`): a named asyncio.Queue living in a dedicated
actor, usable from any driver/worker, with blocking/non-blocking puts and
gets, batch variants, and shutdown.
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self._active = 0   # blocking puts/gets currently in flight

    async def put(self, item, timeout: Optional[float] = None):
        self._active += 1
        try:
            if timeout is None:
                await self._q.put(item)
                return True
            try:
                await asyncio.wait_for(self._q.put(item), timeout)
                return True
            except asyncio.TimeoutError:
                return False
        finally:
            self._active -= 1

    async def put_nowait(self, item):
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def put_nowait_batch(self, items: List[Any]):
        if self._q.maxsize and self._q.qsize() + len(items) > self._q.maxsize:
            return False
        for item in items:
            self._q.put_nowait(item)
        return True

    async def get(self, timeout: Optional[float] = None):
        self._active += 1
        try:
            if timeout is None:
                return True, await self._q.get()
            try:
                return True, await asyncio.wait_for(self._q.get(), timeout)
            except asyncio.TimeoutError:
                return False, None
        finally:
            self._active -= 1

    async def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def get_nowait_batch(self, num_items: int):
        if self._q.qsize() < num_items:
            return None
        return [self._q.get_nowait() for _ in range(num_items)]

    async def qsize(self) -> int:
        return self._q.qsize()

    async def empty(self) -> bool:
        return self._q.empty()

    async def drain(self) -> bool:
        """Graceful-shutdown barrier: resolves once no blocking put/get is
        in flight (the client caps the wait, so a forever-blocked get
        cannot hang shutdown)."""
        while self._active > 0:
            await asyncio.sleep(0.01)
        return True

    async def full(self) -> bool:
        return self._q.full()


class Queue:
    """Driver/worker-shared FIFO queue.

    Example:
        q = Queue(maxsize=100)
        q.put(1); assert q.get() == 1
    """

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 64)
        self.maxsize = maxsize
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    def __reduce__(self):
        return _rebuild_queue, (self.actor, self.maxsize)

    # ---------------------------------------------------------------- put
    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full("queue is full")
            return
        if not ray_tpu.get(self.actor.put.remote(item, timeout)):
            raise Full(f"put timed out after {timeout}s")

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(list(items))):
            raise Full("batch does not fit in queue")

    # ---------------------------------------------------------------- get
    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty("queue is empty")
            return item
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty(f"get timed out after {timeout}s")
        return item

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        out = ray_tpu.get(self.actor.get_nowait_batch.remote(num_items))
        if out is None:
            raise Empty(f"fewer than {num_items} items in queue")
        return out

    # -------------------------------------------------------------- admin
    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def size(self) -> int:
        return self.qsize()

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self, force: bool = False) -> None:
        """force=False waits for already-submitted actor calls to finish
        before killing the queue actor (reference semantics:
        `ray.util.queue.Queue.shutdown`); force=True kills immediately."""
        if not force:
            try:
                ray_tpu.get(self.actor.drain.remote(), timeout=30)
            except Exception:
                pass
        ray_tpu.kill(self.actor)


def _rebuild_queue(actor, maxsize):
    q = Queue.__new__(Queue)
    q.actor = actor
    q.maxsize = maxsize
    return q
