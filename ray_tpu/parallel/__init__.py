from ray_tpu.parallel.mesh import (
    MESH_AXES,
    MeshConfig,
    build_mesh,
    constrain,
    current_mesh,
    logical_to_spec,
    named_sharding,
    use_mesh,
)

__all__ = [
    "MESH_AXES", "MeshConfig", "build_mesh", "constrain", "current_mesh",
    "logical_to_spec", "named_sharding", "use_mesh",
]
