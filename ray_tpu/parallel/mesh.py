"""Device mesh construction and logical-axis sharding rules.

This is the TPU-native replacement for the reference's process-group plumbing
(`python/ray/util/collective/collective.py`, `python/ray/train/v2/jax/config.py`):
instead of wiring NCCL communicators between actors, we build a single
`jax.sharding.Mesh` over all chips and express every parallelism strategy
(dp/fsdp/sp/tp/ep/pp) as named mesh axes. XLA inserts the ICI/DCN collectives.

Axis order is slowest-varying first so that DCN-crossing axes (dp, pp) get the
outermost mesh dimensions and ICI-local axes (tp) the innermost, matching the
physical topology (tp traffic must ride ICI; dp allreduces tolerate DCN).
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import threading
from typing import Any, Mapping, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

# Canonical mesh axis names, outermost (DCN-tolerant) to innermost (ICI-only).
MESH_AXES = ("pp", "dp", "fsdp", "sp", "ep", "tp")

# Hierarchical data-parallel sub-axes: the dp axis expressed as
# (slow-fabric hosts) x (fast-fabric local devices), so a compiled train
# step can emit reduce-scatter/all-gather over `dp_intra` (ICI) and keep
# the `dp_inter` (DCN) hop shard-sized — the two-level schedule INSIDE
# the program instead of staged in Python (util/collective/hierarchy.py).
DP_SUB_AXES = ("dp_inter", "dp_intra")
HIER_MESH_AXES = ("pp", "dp_inter", "dp_intra", "fsdp", "sp", "ep", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Degree of each parallelism axis. Product must equal the device count.

    Any axis left at -1 is inferred to absorb the remaining devices (at most
    one axis may be -1).
    """

    pp: int = 1    # pipeline stages
    dp: int = 1    # pure data parallel (gradients allreduced)
    fsdp: int = 1  # data parallel with parameters sharded (ZeRO-3 style)
    sp: int = 1    # sequence/context parallel (ring attention axis)
    ep: int = 1    # expert parallel (MoE)
    tp: int = 1    # tensor (megatron) parallel

    def degrees(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in MESH_AXES}

    def resolved(self, n_devices: int) -> "MeshConfig":
        d = self.degrees()
        unknown = [a for a, v in d.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one axis may be -1, got {unknown}")
        known = math.prod(v for v in d.values() if v != -1)
        if unknown:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {known}")
            d[unknown[0]] = n_devices // known
        if math.prod(d.values()) != n_devices:
            raise ValueError(
                f"mesh {d} has {math.prod(d.values())} slots but {n_devices} devices")
        return MeshConfig(**d)


def adaptive_mesh_config(
    requested: Union[MeshConfig, Mapping[str, int]],
    n_devices: int,
    shrink_axes: Sequence[str] = ("dp", "fsdp"),
) -> MeshConfig:
    """Fit `requested` to what `n_devices` can actually hold.

    Elastic-training companion to `MeshConfig.resolved`: instead of
    erroring when the device count no longer matches (a worker or host
    was lost mid-run), shrink the `shrink_axes` — outermost data axes
    first, the ones whose degree is a pure throughput knob — toward 1
    until the mesh fits, and grow them back (up to the requested degree)
    when capacity returns. Model-parallel axes (tp/pp/ep/sp) are never
    changed: their degree is baked into parameter shapes, so a mesh that
    cannot hold them is a hard error, same as before.

    The returned config may use only a SUBSET of `n_devices` (odd
    survivor counts); build the mesh over `devices[:cfg.resolved-total]`.
    """
    if isinstance(requested, Mapping):
        requested = MeshConfig(**dict(requested))
    d = requested.degrees()
    if any(v == -1 for v in d.values()):
        return requested.resolved(n_devices)
    fixed = math.prod(v for a, v in d.items() if a not in shrink_axes)
    if fixed <= 0 or n_devices < fixed:
        raise ValueError(
            f"{n_devices} devices cannot hold fixed axes "
            f"{ {a: v for a, v in d.items() if a not in shrink_axes} } "
            f"(product {fixed})")
    # floor, don't reject: 3 survivors with tp=2 means a dp=1,tp=2 mesh on
    # 2 of them — the caller slices devices[:cfg.total] (an odd survivor
    # count mid-recovery must not hard-error the restart)
    budget = n_devices // fixed
    # shrink the LAST shrink axis first (innermost data axis) so the
    # outer/data-parallel degree survives longest; grow in reverse
    for axis in reversed(list(shrink_axes)):
        while d[axis] > 1 and math.prod(d[a] for a in shrink_axes) > budget:
            d[axis] = (d[axis] // 2) if d[axis] % 2 == 0 else 1
    got = math.prod(d[a] for a in shrink_axes)
    if got > budget:
        raise ValueError(
            f"cannot shrink {tuple(shrink_axes)} below {got} to fit "
            f"budget {budget} ({n_devices} devices)")
    # absorb leftover capacity into the FIRST shrink axis (grow-back on
    # rejoin), never past the requested degree
    first = list(shrink_axes)[0]
    while (got * 2 <= budget
           and d[first] * 2 <= requested.degrees()[first]):
        d[first] *= 2
        got *= 2
    return MeshConfig(**d)


def build_mesh(
    config: Union[MeshConfig, Mapping[str, int], None] = None,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Build a Mesh with the canonical axis names.

    `devices` defaults to all local jax devices. The device array is reshaped
    in canonical axis order; on real slices callers should pass devices from
    `jax.experimental.mesh_utils.create_device_mesh` for ICI-optimal layout
    (we do that automatically when the topology is a known slice shape).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if config is None:
        config = MeshConfig(dp=len(devices))
    if isinstance(config, Mapping):
        config = MeshConfig(**dict(config))
    config = config.resolved(len(devices))
    shape = tuple(config.degrees()[a] for a in MESH_AXES)
    try:
        # ICI-aware layout when available (real TPU slices).
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def build_hierarchical_mesh(
    config: Union[MeshConfig, Mapping[str, int], None] = None,
    devices: Optional[Sequence[Any]] = None,
    topology: Optional[Any] = None,
) -> Mesh:
    """`build_mesh` variant whose dp axis is split into the
    `(dp_inter, dp_intra)` sub-axes of a hosts x local-devices
    `collective.Topology`.

    Flat-dp callers are untouched: `build_mesh` still produces the
    canonical single-`dp` mesh, and every spec written against it keeps
    working. This factory is opt-in for the fused hierarchical gradient
    sync (`train/spmd.py`): the dp degree must equal
    `topology.inter * topology.intra`, and the dp slot of the device
    array is laid out row-major hosts x local — the same layout
    `Topology.mesh` uses — so `dp_inter` groups cross the slow fabric
    and `dp_intra` groups stay on the fast one.

    `topology` defaults to the physical layout of the dp devices
    (`topology_from_devices` shape: processes x min local chips); on a
    single-process CI backend that degenerates to inter=1, so tests pass
    an explicit `Topology(2, 2)` to emulate 2 hosts x 2 devices.
    """
    from ray_tpu.util.collective.hierarchy import Topology

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if config is None:
        config = MeshConfig(dp=len(devices))
    if isinstance(config, Mapping):
        config = MeshConfig(**dict(config))
    config = config.resolved(len(devices))
    if topology is None:
        phys = topology_from_devices(devices)
        if config.dp % max(phys.intra, 1) == 0 and phys.intra > 1:
            topology = Topology(inter=config.dp // phys.intra,
                                intra=phys.intra)
        else:
            topology = Topology(inter=config.dp, intra=1)
    if topology.inter * topology.intra != config.dp:
        raise ValueError(
            f"dp={config.dp} devices cannot form a "
            f"{topology.inter}x{topology.intra} (inter x intra) topology")
    d = config.degrees()
    shape = (d["pp"], topology.inter, topology.intra, d["fsdp"], d["sp"],
             d["ep"], d["tp"])
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, HIER_MESH_AXES)


def dp_axis_names(mesh: Mesh) -> tuple:
    """The mesh axes carrying pure data parallelism: the
    `(dp_inter, dp_intra)` sub-axes on a hierarchical mesh, the single
    `dp` axis otherwise. Empty when the mesh has neither."""
    names = tuple(getattr(mesh, "axis_names", ()) or ())
    if all(a in names for a in DP_SUB_AXES):
        return DP_SUB_AXES
    if "dp" in names:
        return ("dp",)
    return ()


def is_hierarchical_mesh(mesh: Mesh) -> bool:
    return dp_axis_names(mesh) == DP_SUB_AXES


def hier_topology(mesh: Mesh):
    """The `collective.Topology` a hierarchical mesh's dp sub-axes
    express, with axis names bound to the MESH axis names (so program
    builders written against `Topology.inter_axis`/`intra_axis` lower
    over this mesh directly)."""
    if not is_hierarchical_mesh(mesh):
        raise ValueError(
            f"mesh axes {tuple(mesh.axis_names)} carry no "
            f"(dp_inter, dp_intra) sub-axes; build_hierarchical_mesh makes "
            f"one")
    from ray_tpu.util.collective.hierarchy import Topology

    return Topology(inter=int(mesh.shape[DP_SUB_AXES[0]]),
                    intra=int(mesh.shape[DP_SUB_AXES[1]]),
                    inter_axis=DP_SUB_AXES[0], intra_axis=DP_SUB_AXES[1])


def rules_for_mesh(mesh: Mesh,
                   rules: Optional["LogicalRules"] = None) -> dict:
    """DEFAULT_RULES (plus overrides) rewritten for `mesh`'s dp spelling:
    on a hierarchical mesh every rule naming `dp` names the
    `(dp_inter, dp_intra)` pair instead, so logical specs like "batch"
    shard over both sub-axes without model code changing."""
    merged = {**DEFAULT_RULES, **(rules or {})}
    if not is_hierarchical_mesh(mesh):
        return merged
    out = {}
    for k, v in merged.items():
        axes = (v,) if isinstance(v, str) else v
        if axes and "dp" in axes:
            axes = tuple(a for ax in axes
                         for a in (DP_SUB_AXES if ax == "dp" else (ax,)))
            out[k] = axes
        else:
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# Logical axis rules (flax-style) and a current-mesh context so model code can
# write `constrain(x, "batch", "seq", "embed")` without threading a mesh.
# ---------------------------------------------------------------------------

# logical axis -> mesh axis (or tuple of mesh axes, or None for replicated)
LogicalRules = Mapping[str, Union[str, tuple, None]]

DEFAULT_RULES: dict[str, Union[str, tuple, None]] = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",            # activation sequence axis (context parallelism)
    "embed": "fsdp",        # parameter hidden axis: ZeRO-3 shard over fsdp
    "mlp": "tp",
    "heads": "tp",
    "kv": None,
    "vocab": "tp",
    "expert": "ep",
    "stage": "pp",
    "layers": None,
}


class _MeshContext(threading.local):
    def __init__(self):
        self.mesh: Optional[Mesh] = None
        self.rules: dict[str, Any] = dict(DEFAULT_RULES)


_ctx = _MeshContext()


@contextlib.contextmanager
def use_mesh(mesh: Mesh, rules: Optional[LogicalRules] = None):
    """Install `mesh` (and optionally override logical rules) for this thread."""
    prev_mesh, prev_rules = _ctx.mesh, _ctx.rules
    _ctx.mesh = mesh
    if rules is not None:
        _ctx.rules = {**DEFAULT_RULES, **rules}
    try:
        yield mesh
    finally:
        _ctx.mesh, _ctx.rules = prev_mesh, prev_rules


def current_mesh() -> Optional[Mesh]:
    return _ctx.mesh


def logical_to_spec(*logical_axes: Optional[str], rules: Optional[LogicalRules] = None) -> PartitionSpec:
    """Map logical axis names to a PartitionSpec via the active rules.

    Mesh axes consumed by an earlier logical axis are dropped (a mesh axis may
    only appear once in a PartitionSpec).
    """
    rules = dict(rules) if rules is not None else _ctx.rules
    used: set = set()
    parts = []
    for name in logical_axes:
        if name is None:
            parts.append(None)
            continue
        mesh_axes = rules.get(name)
        if mesh_axes is None:
            parts.append(None)
            continue
        if isinstance(mesh_axes, str):
            mesh_axes = (mesh_axes,)
        keep = tuple(a for a in mesh_axes if a not in used)
        used.update(keep)
        if not keep:
            parts.append(None)
        elif len(keep) == 1:
            parts.append(keep[0])
        else:
            parts.append(keep)
    while parts and parts[-1] is None:
        parts.pop()
    return PartitionSpec(*parts)


def named_sharding(*logical_axes: Optional[str], mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or current_mesh()
    if mesh is None:
        raise RuntimeError("no active mesh: wrap in `use_mesh(mesh)` or pass mesh=")
    return NamedSharding(mesh, logical_to_spec(*logical_axes))


_constrain_suppressed = threading.local()


@contextlib.contextmanager
def suppress_constraints():
    """Disable `constrain` inside the with-block (trace-time scope).

    FULL-manual shard_map regions (the CPU pipeline lowering in
    parallel/pipeline.py) reject with_sharding_constraint over manual
    axes; stage functions written for auto sharding still call
    `constrain`, so the manual lowering wraps their trace in this."""
    prev = getattr(_constrain_suppressed, "on", False)
    _constrain_suppressed.on = True
    try:
        yield
    finally:
        _constrain_suppressed.on = prev


def constrain(x, *logical_axes: Optional[str]):
    """`with_sharding_constraint` by logical axis names; no-op without a mesh."""
    mesh = current_mesh()
    if mesh is None or getattr(_constrain_suppressed, "on", False):
        return x
    spec = logical_to_spec(*logical_axes)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def axis_size(mesh: Mesh, *axes: str) -> int:
    return math.prod(mesh.shape[a] for a in axes)


def topology_from_devices(devices: Optional[Sequence[Any]] = None):
    """Physical hosts x local-devices `collective.Topology` of a device
    list (default: all devices) — the descriptor the hierarchical
    collectives consume. Processes are the inter (DCN) axis, each
    process's local chips the intra (ICI) axis; asymmetric hosts
    truncate to the common minimum so the 2D mesh stays rectangular."""
    from ray_tpu.util.collective.hierarchy import (Topology,
                                                   device_rows_by_process)

    rows = device_rows_by_process(
        list(devices) if devices is not None else jax.devices())
    return Topology(inter=len(rows), intra=min(len(r) for r in rows))
