"""Pipeline parallelism: GPipe-style microbatch pipeline over the `pp` mesh axis.

The reference gets pipeline parallelism two ways (SURVEY.md §2.13): vLLM's
multi-node PP driven through placement groups, and Compiled Graphs
(`python/ray/dag/compiled_dag_node.py`) whose per-actor READ/COMPUTE/WRITE
schedules pipeline NCCL send/recv between stages. The TPU-native answer keeps
the whole pipeline INSIDE one XLA program: stages are a `pp` mesh axis, stage
hand-off is `lax.ppermute` riding the ICI ring, and the schedule is a
`lax.scan` over M + F - 1 ticks — XLA overlaps the permute with the next
tick's compute, no host in the loop.

Design (partial-manual shard_map):
- only `pp` is manual (`axis_names={'pp'}`); dp/fsdp/tp stay auto, so the
  stage function can keep its ordinary sharding annotations and XLA still
  inserts dp gradient allreduces etc.;
- stage params have a leading stage dim sharded over `pp`; each instance
  squeezes its own stage's slice;
- microbatch schedule: at tick t, stage 0 injects microbatch t (t < M), the
  last stage emits microbatch t-(F-1); a final masked `psum` replicates the
  output to every stage so downstream (loss/unembed) code sees a plain
  replicated-over-pp activation.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.mesh import current_mesh
from ray_tpu.util.collective.hierarchy import (account_collective,
                                               ring_perm)
from ray_tpu.utils.jax_compat import axis_index_operand
from ray_tpu.utils.jax_compat import shard_map as _compat_shard_map


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    n_microbatches: int,
    mesh=None,
    axis: str = "pp",
) -> jax.Array:
    """Run `stage_fn` as a `pp`-deep pipeline over microbatches of `x`.

    stage_params: pytree whose every leaf has leading dim = pp degree
      (stage-stacked), sharded over `axis`.
    x: [B, ...] activations; B % n_microbatches == 0.
    stage_fn(params_for_one_stage, x_mb) -> x_mb.
    """
    mesh = mesh or current_mesh()
    if mesh is None:
        raise RuntimeError("pipeline_apply needs a mesh (use_mesh or mesh=)")
    F = mesh.shape[axis]
    if F == 1:
        sp = jax.tree.map(lambda a: a[0], stage_params)
        return stage_fn(sp, x)

    M = n_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by n_microbatches {M}")
    if M < F:
        raise ValueError(f"n_microbatches {M} < pipeline depth {F}: "
                         "bubble would dominate; use M >= pp")
    # On CPU only, the shard_map boundary runs in f32: XLA's CPU backend (the
    # dryrun/test platform) miscompiles sub-group bf16 psum in partial-manual
    # regions ("Invalid binary instruction opcode copy" CHECK), and the f32
    # boundary also covers the backward-pass psum of the replicated input's
    # cotangent. On TPU the bug doesn't exist and bf16 boundaries halve the
    # buffer + ICI psum bytes. Compute inside the stages stays in x.dtype.
    compute_dtype = x.dtype
    on_cpu = jax.default_backend() == "cpu"
    boundary_dtype = jnp.float32 if on_cpu else compute_dtype
    xs = x.reshape(M, B // M, *x.shape[1:]).astype(boundary_dtype)
    # Lowering mode. TPU: partial-manual (only `pp` manual) so stage_fn
    # keeps its auto dp/tp shardings. CPU (the dryrun/test platform):
    # jax 0.4.x's SPMD partitioner CHECK-crashes on sub-group ppermute in
    # a partial-manual region ("target.IsManualSubgroup() ==
    # sharding().IsManualSubgroup()"), so the region goes FULL-manual over
    # every mesh axis — numerically identical (params replicated over the
    # data axes transpose to a psum'd gradient, verified by the pipeline
    # train test), with the microbatch batch dim explicitly split over
    # the first divisible data axis to keep dp compute parallel.
    manual_axes = set(mesh.axis_names) if on_cpu else {axis}
    batch_axis = None
    if on_cpu:
        for cand in ("dp", "fsdp", "data"):
            if (cand != axis and cand in mesh.shape
                    and (B // M) % mesh.shape[cand] == 0):
                batch_axis = cand
                break
    xs_spec = P(None, batch_axis) if batch_axis else P()
    if not isinstance(x, jax.core.Tracer):
        # eager entry: account the pipeline's stage hand-off wire bytes
        # ((M+F-1) ticks, each stage forwards one microbatch activation).
        # The ring moves compute_dtype state (spmd_fn casts back before
        # the ppermute) — size it off x, not the f32 boundary buffer.
        mb_bytes = x.nbytes // M
        account_collective("pipeline.ppermute", (M + F - 1) * F * mb_bytes,
                           str(compute_dtype), hop="intra")

    def spmd_fn(stage_p, xs, stage_ids):
        xs = xs.astype(compute_dtype)
        stage_p = jax.tree.map(lambda a: a[0], stage_p)   # this stage's slice
        # operand-derived stage index: lax.axis_index in a partial-manual
        # region lowers to a PartitionId instruction jax 0.4.x's SPMD
        # partitioner rejects (see utils/jax_compat.axis_index_operand)
        stage = stage_ids[0]
        state = jnp.zeros_like(xs[0])
        outs = jnp.zeros_like(xs)

        def tick(carry, t):
            state, outs = carry
            # stage 0 injects microbatch t
            inp = lax.dynamic_index_in_dim(xs, jnp.minimum(t, M - 1), 0,
                                           keepdims=False)
            state = jnp.where((stage == 0) & (t < M), inp, state)
            state = stage_fn(stage_p, state)
            # last stage emits microbatch t-(F-1)
            out_t = t - (F - 1)
            idx = jnp.clip(out_t, 0, M - 1)
            cur = lax.dynamic_index_in_dim(outs, idx, 0, keepdims=False)
            new = jnp.where((stage == F - 1) & (out_t >= 0), state, cur)
            outs = lax.dynamic_update_index_in_dim(outs, new, idx, 0)
            # rotate activations one stage forward (ICI ring; the
            # canonical collective-layer ring hop)
            state = lax.ppermute(state, axis, ring_perm(F))
            return (state, outs), None

        (state, outs), _ = lax.scan(tick, (state, outs),
                                    jnp.arange(M + F - 1))
        # replicate the last stage's outputs to every stage (psum in the
        # boundary dtype — see dtype note above)
        outs = outs.astype(boundary_dtype)
        outs = lax.psum(
            jnp.where(stage == F - 1, outs, jnp.zeros_like(outs)), axis)
        return outs

    import contextlib

    from ray_tpu.parallel import mesh as mesh_mod

    # full-manual regions reject sharding constraints over manual axes;
    # auto-sharding-style stage functions still call mesh.constrain
    cm = (mesh_mod.suppress_constraints() if manual_axes != {axis}
          else contextlib.nullcontext())
    with cm:
        out = _compat_shard_map(
            spmd_fn,
            mesh=mesh,
            in_specs=(P(axis), xs_spec, P(axis)),
            out_specs=xs_spec,
            axis_names=manual_axes,
            check_vma=False,
        )(stage_params, xs, axis_index_operand(F))
    return out.astype(compute_dtype).reshape(B, *x.shape[1:])


def stack_stages(block_params: Any, n_stages: int) -> Any:
    """[L, ...]-stacked block params -> [n_stages, L/n_stages, ...]."""

    def reshape(a):
        L = a.shape[0]
        if L % n_stages:
            raise ValueError(f"{L} layers not divisible by {n_stages} stages")
        return a.reshape(n_stages, L // n_stages, *a.shape[1:])

    return jax.tree.map(reshape, block_params)


def make_stage_fn(block_fn: Callable[[jax.Array, Any], jax.Array],
                  remat: bool = True) -> Callable:
    """Lift a single-block fn (x, block_params) -> x into a stage fn that
    scans its stage's [L/F, ...] blocks."""
    body = jax.checkpoint(block_fn) if remat else block_fn

    def stage_fn(stage_p, x):
        x, _ = lax.scan(lambda c, bp: (body(c, bp), None), x, stage_p)
        return x

    return stage_fn


# ---------------------------------------------------------------------------
# Channel-driven compiled 1F1B schedule (SURVEY §3.7 Compiled Graphs).
#
# `pipeline_apply` above keeps the whole pipeline inside ONE XLA program —
# right when every stage fits one mesh. The classes below are the
# HOST-level pipeline: stages are long-lived actors (one per host/slice,
# possibly on different nodes), and the per-microbatch hand-offs ride the
# same pre-negotiated channels as compiled DAGs — local shm rings between
# co-located stages, `RemoteChannelReader` RPC edges across nodes, and
# (tensor_transport="device") DLPack descriptors through the PR 7
# device-object plane so activations never leave device memory for a
# co-located consumer. The 1F1B order (warmup forwards, steady
# one-forward-one-backward, cooldown backwards) bounds live activations
# per stage at pipeline depth, and the ring depth (`max_inflight`) is
# what lets a stage run ahead instead of serializing on the slowest
# neighbour — max_inflight=1 degenerates to lock-step single-slot
# hand-offs. The scheduler participates only at start(): a warm step is
# shm writes + condvar wakes, zero control-plane RPCs.
# ---------------------------------------------------------------------------


def mse_loss(pred: jax.Array, target: jax.Array) -> jax.Array:
    """Default last-stage loss for ChannelPipelineStage (top-level: must
    pickle by reference into stage actors)."""
    return jnp.mean((pred - target) ** 2)


def mlp_stage_fn(params: dict, x: jax.Array) -> jax.Array:
    """Reference stage for tests/benchmarks: one tanh MLP layer."""
    return jnp.tanh(x @ params["w"] + params["b"])


def init_mlp_stage(key, d_in: int, d_out: int, scale: float = 0.3) -> dict:
    k1, _ = jax.random.split(jax.random.key(key) if isinstance(key, int)
                             else key)
    return {"w": jax.random.normal(k1, (d_in, d_out)) * scale,
            "b": jnp.zeros((d_out,))}


class ChannelPipelineStage:
    """One pipeline stage as a long-lived actor: holds its params, a
    jitted forward, a jitted VJP backward, and (last stage) a jitted
    loss-and-grad. Wrap with `ray_tpu.remote` (or use
    `CompiledPipeline.build_stages`). Two drive modes:

    - eager: the driver calls `fwd_eager`/`bwd_eager` per microbatch
      (GPipe over ordinary actor RPCs — the baseline the compiled mode
      is measured against);
    - compiled: `pp_stage_loop(cfg)` attaches pre-negotiated channels
      and runs the 1F1B schedule until the input channel closes.
    """

    def __init__(self, stage_fn: Callable, params: Any, *,
                 position: int, n_stages: int, lr: float = 0.05,
                 loss_fn: Optional[Callable] = None):
        self.position = int(position)
        self.n_stages = int(n_stages)
        self.is_first = self.position == 0
        self.is_last = self.position == self.n_stages - 1
        self.lr = float(lr)
        self.params = params
        self._stage_fn = stage_fn
        self._fwd = jax.jit(stage_fn)

        def _bwd(p, x, g):
            _, vjp = jax.vjp(stage_fn, p, x)
            return vjp(g)

        self._bwd = jax.jit(_bwd)
        if self.is_last:
            loss_fn = loss_fn or mse_loss

            def _loss(p, x, y):
                return loss_fn(stage_fn(p, x), y)

            self._lossgrad = jax.jit(jax.value_and_grad(_loss,
                                                        argnums=(0, 1)))
        self._apply = jax.jit(
            lambda p, g, s: jax.tree.map(lambda a, b: a - s * b, p, g))
        self._acc = None
        self._stash: dict = {}
        self._losses: list = []
        self.steps_done = 0
        self._dev_refs: list = []
        # eager calls arrive in submission order but may EXECUTE
        # concurrently (the actor leaves executor room for control
        # calls); the lock serializes them back into schedule order
        import threading

        self._eager_lock = threading.Lock()

    # ------------------------------------------------------------ common
    def _accumulate(self, dp) -> None:
        self._acc = dp if self._acc is None else jax.tree.map(
            jnp.add, self._acc, dp)

    def apply_grads(self, n_microbatches: int, _after=None) -> bool:
        if self._acc is not None:
            self.params = self._apply(self.params, self._acc,
                                      self.lr / n_microbatches)
            self._acc = None
        self.steps_done += 1
        return True

    def get_params(self):
        import numpy as np

        return jax.tree.map(np.asarray, self.params)

    # ------------------------------------------------- eager (RPC) drive
    # `_after` is a sequencing-only dependency: the driver threads each
    # stage's previous op ref through it so ops run in schedule order
    # even when the actor executes calls concurrently (lock wakeup order
    # is not FIFO; argument resolution is).
    def fwd_eager(self, mb: int, x, y=None, _after=None):
        with self._eager_lock:
            x = jnp.asarray(x)
            if self.is_last:
                loss, (dp, dx) = self._lossgrad(self.params, x,
                                                jnp.asarray(y))
                self._accumulate(dp)
                self._losses.append(float(loss))
                self._stash[mb] = dx
                return None
            act = self._fwd(self.params, x)
            self._stash[mb] = x
            import numpy as np

            return np.asarray(act)

    def bwd_eager(self, mb: int, g=None, _after=None):
        import numpy as np

        with self._eager_lock:
            if self.is_last:
                return np.asarray(self._stash.pop(mb))
            dp, dx = self._bwd(self.params, self._stash.pop(mb),
                               jnp.asarray(g))
            self._accumulate(dp)
            return None if self.is_first else np.asarray(dx)

    def pop_mean_loss(self, _after=None) -> float:
        losses, self._losses = self._losses, []
        return float(sum(losses) / max(1, len(losses)))

    # ------------------------------------------- compiled (channel) drive
    def _wrap(self, arr, transport, ring: int):
        import numpy as np

        if transport == "device":
            from ray_tpu.core.api import _global_client
            from ray_tpu.dag.runtime import DEVICE_DESC

            oref = _global_client().put_device(arr)
            # hold enough generations to cover the ring depth plus the
            # value a reader may still be fetching
            self._dev_refs.append(oref)
            while len(self._dev_refs) > 2 * ring + 2:
                self._dev_refs.pop(0)
            return {DEVICE_DESC: oref.binary()}
        return np.asarray(arr)

    def _schedule(self, M: int) -> list:
        """1F1B op order for this stage: warmup forwards, steady
        (forward, backward) pairs, cooldown backwards."""
        W = min(self.n_stages - 1 - self.position, M)
        ops = [("F", k) for k in range(W)]
        for k in range(M - W):
            ops.append(("F", W + k))
            ops.append(("B", k))
        ops.extend(("B", k) for k in range(M - W, M))
        return ops

    def _stage_span(self, carrier, t0: float):
        """Record this stage's forward span for a sampled microbatch
        (backdated over the compute it just ran) and return the child
        carrier the NEXT stage parents to — the per-hop link in the
        compiled 1F1B submit→stage→…→stage span chain. None when the
        microbatch is untraced."""
        if carrier is None:
            return None
        try:
            from ray_tpu.util import tracing

            dur = time.perf_counter() - t0
            with tracing.start_span(
                    f"pp.stage{self.position}.fwd", carrier=carrier,
                    attributes={"ray_tpu.op": "pp_stage",
                                "position": self.position}) as sp:
                if sp is None:
                    return None
                sp.start_ts = time.time() - dur
                return {"traceparent": sp.traceparent()}
        except Exception:
            return None

    def _publish_ring_telemetry(self, key: str, *endpoints) -> None:
        """Snapshot this stage's LOCAL ring handles (remote-reader edges
        are sampled by their hosting process) into the hot-path
        observatory, labelled by edge role."""
        from ray_tpu.dag.channel import Channel, publish_ring_stats

        snaps = {}
        for label, ep in zip(("in", "out", "gin", "gout"), endpoints):
            if isinstance(ep, Channel):
                try:
                    snaps[label] = ep.snapshot()
                except Exception:
                    pass
        if snaps:
            publish_ring_stats("pipeline", f"{key}/stage{self.position}",
                               snaps)

    def pp_stage_loop(self, cfg: dict) -> dict:
        """Attach this stage's pre-negotiated channel edges and run 1F1B
        steps until the upstream channel closes (driver teardown)."""
        from ray_tpu.dag.channel import (Channel, ChannelClosedError,
                                         RemoteChannelReader)
        from ray_tpu.dag.runtime import materialize_channel_value

        def endpoint(ref):
            if ref is None:
                return None
            kind, val = ref
            if kind == "chan":
                return Channel.attach(val)
            return RemoteChannelReader(*val)

        in_r = endpoint(cfg["in"])
        out_w = endpoint(cfg.get("out"))
        gin_r = endpoint(cfg.get("gin"))
        gout_w = endpoint(cfg.get("gout"))
        loss_w = endpoint(cfg.get("loss"))
        M = int(cfg["M"])
        ring = int(cfg.get("ring", 1))
        transport = cfg.get("transport")
        key = str(cfg.get("key", "pp"))
        ops = self._schedule(M)
        steps = 0
        last_telem = 0.0
        try:
            from ray_tpu.core import config as _cfg

            telem_interval = float(_cfg.get("ring_telemetry_interval_s"))
        except Exception:
            telem_interval = 0.0
        try:
            while True:
                losses = []
                for op, k in ops:
                    if op == "F":
                        # a sampled microbatch carries a W3C carrier as a
                        # third tuple element (CompiledPipeline.step /
                        # the upstream stage's _stage_span)
                        item = in_r.read()
                        carrier = item[2] if len(item) > 2 else None
                        x, y = item[0], item[1]
                        x = jnp.asarray(materialize_channel_value(x))
                        t0 = time.perf_counter()
                        if self.is_last:
                            loss, (dp, dx) = self._lossgrad(
                                self.params, x, jnp.asarray(y))
                            self._accumulate(dp)
                            losses.append(float(loss))
                            if gout_w is not None:
                                gout_w.write(self._wrap(dx, transport, ring))
                            self._stage_span(carrier, t0)
                        else:
                            act = self._fwd(self.params, x)
                            self._stash[k] = x
                            child = self._stage_span(carrier, t0)
                            payload = (self._wrap(act, transport, ring), y)
                            if child is not None:
                                payload = payload + (child,)
                            out_w.write(payload)
                    elif not self.is_last:
                        g = jnp.asarray(materialize_channel_value(
                            gin_r.read()))
                        dp, dx = self._bwd(self.params, self._stash.pop(k), g)
                        self._accumulate(dp)
                        if gout_w is not None:
                            gout_w.write(self._wrap(dx, transport, ring))
                self.apply_grads(M)
                if loss_w is not None:
                    loss_w.write(float(sum(losses) / max(1, len(losses))))
                steps += 1
                if telem_interval > 0 \
                        and time.monotonic() - last_telem > telem_interval:
                    last_telem = time.monotonic()
                    self._publish_ring_telemetry(key, in_r, out_w,
                                                 gin_r, gout_w)
        except ChannelClosedError:
            pass
        finally:
            # propagate shutdown downstream so every stage's loop exits
            for ch in (out_w, gout_w, loss_w):
                if ch is not None:
                    try:
                        ch.close()
                    except Exception:
                        pass
            self._stash.clear()
            self._dev_refs.clear()
        return {"steps": steps, "position": self.position}


class CompiledPipeline:
    """Driver handle for a channel-driven 1F1B pipeline over stage
    actors. `start()` negotiates every channel once (the only
    control-plane work); `step(x, y)` streams microbatches through the
    input ring and blocks on the loss ring — zero per-step RPCs when the
    stages are co-located, RemoteChannelReader edges otherwise."""

    def __init__(self, stage_actors, *, n_microbatches: int,
                 max_inflight: Optional[int] = None,
                 channel_capacity: int = 4 << 20,
                 tensor_transport: Optional[str] = None,
                 step_timeout: float = 120.0):
        if not stage_actors:
            raise ValueError("need at least one stage actor")
        self.stages = list(stage_actors)
        self.M = int(n_microbatches)
        F = len(self.stages)
        self.max_inflight = int(max_inflight or max(2, min(self.M, F + 1)))
        self.capacity = channel_capacity
        self.transport = tensor_transport
        self.step_timeout = step_timeout
        self._started = False
        self._closed = False
        self._loop_refs = []
        self._remote_created = []
        self.key = "pp"               # replaced by the start() tag
        self._trace_seq = 0
        self._last_telem = 0.0

    @staticmethod
    def build_stages(stage_fns, params_list, *, lr: float = 0.05,
                     loss_fn: Optional[Callable] = None,
                     actor_options: Optional[list] = None):
        """Create one ChannelPipelineStage actor per (stage_fn, params).
        `actor_options[i]` (e.g. {"resources": {...}}) pins placement."""
        import ray_tpu

        F = len(params_list)
        fns = (stage_fns if isinstance(stage_fns, (list, tuple))
               else [stage_fns] * F)
        actors = []
        for i, (fn, p) in enumerate(zip(fns, params_list)):
            opts = dict((actor_options[i] if actor_options else {}) or {})
            # the compiled stage loop occupies one executor thread for its
            # lifetime; leave room for control calls (get_params, eager)
            opts.setdefault("max_concurrency", 4)
            cls = ray_tpu.remote(**opts)(ChannelPipelineStage)
            actors.append(cls.remote(
                fn, p, position=i, n_stages=F, lr=lr,
                loss_fn=loss_fn if i == F - 1 else None))
        return actors

    # ------------------------------------------------------------ bring-up
    def start(self) -> None:
        import os as _os

        from ray_tpu.core.api import _global_client
        from ray_tpu.dag.channel import Channel, RemoteChannelReader

        client = _global_client()
        my_node = client.node_id.binary()
        my_addr = ("127.0.0.1", client.direct_port)
        F = len(self.stages)

        addr, node = [], []
        for s in self.stages:
            reply = client.head_request("get_actor_address",
                                        actor_id=s._actor_id.binary())
            if reply["state"] == "DEAD":
                raise RuntimeError("cannot compile over dead stage actor")
            node.append(reply.get("node_id") or my_node)
            addr.append(tuple(reply["address"]))

        tag = _os.urandom(4).hex()
        self.key = f"pp_{tag}"
        names = {"in": f"rtpu_pp_{tag}_in",
                 "loss": f"rtpu_pp_{tag}_loss"}
        for i in range(F - 1):
            names[f"act{i}"] = f"rtpu_pp_{tag}_a{i}"      # stage i -> i+1
            names[f"grad{i + 1}"] = f"rtpu_pp_{tag}_g{i + 1}"  # i+1 -> i

        # two-phase bring-up: every channel is created in its WRITER's
        # process before any stage loop starts
        self._input = Channel(name=names["in"], capacity=self.capacity,
                              num_readers=1, num_slots=self.max_inflight)

        def create_at(stage_idx: int, name: str) -> None:
            client.direct_request(
                addr[stage_idx], "dag_chan_create", name=name,
                capacity=self.capacity, num_readers=1,
                num_slots=self.max_inflight)
            self._remote_created.append((addr[stage_idx], name))

        for i in range(F - 1):
            create_at(i, names[f"act{i}"])
            create_at(i + 1, names[f"grad{i + 1}"])
        create_at(F - 1, names["loss"])

        def ref_for(name: str, writer_idx: Optional[int],
                    consumer_node: bytes):
            w_node = my_node if writer_idx is None else node[writer_idx]
            w_addr = my_addr if writer_idx is None else addr[writer_idx]
            if w_node == consumer_node:
                return ("chan", name)
            return ("rchan", (name, w_addr))

        for i, s in enumerate(self.stages):
            cfg = {"M": self.M, "ring": self.max_inflight,
                   "transport": self.transport, "key": self.key,
                   "in": (ref_for(names["in"], None, node[i]) if i == 0
                          else ref_for(names[f"act{i - 1}"], i - 1,
                                       node[i])),
                   "out": (ref_for(names[f"act{i}"], i, node[i])
                           if i < F - 1 else None),
                   "gin": (ref_for(names[f"grad{i + 1}"], i + 1, node[i])
                           if i < F - 1 else None),
                   "gout": (ref_for(names[f"grad{i}"], i, node[i])
                            if i > 0 else None),
                   "loss": (ref_for(names["loss"], F - 1, node[i])
                            if i == F - 1 else None)}
            self._loop_refs.append(s.pp_stage_loop.remote(cfg))

        if node[F - 1] == my_node:
            self._loss_r = Channel.attach(names["loss"])
        else:
            self._loss_r = RemoteChannelReader(names["loss"], addr[F - 1])
        self._started = True

    # ------------------------------------------------------------- control
    def _maybe_trace_step(self):
        """1-in-N sampled step tracing (`tracing_compiled_sample_n`, the
        same knob as the serve chain): the returned W3C carrier rides
        microbatch 0's ring tuple, so a sampled step yields the full
        submit→stage→…→stage span chain in the chrome timeline with
        zero extra RPCs. None for unsampled/untraced steps."""
        try:
            from ray_tpu.core import config as _cfg
            from ray_tpu.util import tracing

            n = int(_cfg.get("tracing_compiled_sample_n"))
            if n <= 0 or not tracing.is_recording():
                return None
            seq = self._trace_seq
            self._trace_seq = seq + 1
            if seq % n:
                return None
            with tracing.start_span(
                    "pp.step.submit",
                    attributes={"ray_tpu.op": "pp_submit",
                                "pipeline": self.key,
                                "microbatches": self.M}) as sp:
                if sp is None:
                    return None
                return {"traceparent": sp.traceparent()}
        except Exception:
            return None

    def _telemetry_tick(self) -> None:
        """Time-gated driver-side ring snapshots (input + loss rings,
        when local) into the hot-path observatory."""
        try:
            from ray_tpu.core import config as _cfg

            interval = float(_cfg.get("ring_telemetry_interval_s"))
        except Exception:
            return
        if interval <= 0 or time.monotonic() - self._last_telem < interval:
            return
        self._last_telem = time.monotonic()
        from ray_tpu.dag.channel import Channel, publish_ring_stats

        snaps = {}
        try:
            snaps["in"] = self._input.snapshot()
        except Exception:
            pass
        if isinstance(getattr(self, "_loss_r", None), Channel):
            try:
                snaps["loss"] = self._loss_r.snapshot()
            except Exception:
                pass
        if snaps:
            publish_ring_stats("pipeline", self.key, snaps)

    def step(self, x, y) -> float:
        """Stream one batch through the pipeline as M microbatches;
        returns the step's mean loss. Microbatch writes backpressure on
        the input ring, so up to max_inflight microbatches pipeline into
        the stages while earlier ones are still in flight."""
        if self._closed:
            raise RuntimeError("pipeline was closed")
        if not self._started:
            self.start()
        import numpy as np

        x, y = np.asarray(x), np.asarray(y)
        B = x.shape[0]
        if B % self.M:
            raise ValueError(f"batch {B} not divisible by M={self.M}")
        mb = B // self.M
        carrier = self._maybe_trace_step()
        for k in range(self.M):
            payload = (x[k * mb:(k + 1) * mb], y[k * mb:(k + 1) * mb])
            if k == 0 and carrier is not None:
                payload = payload + (carrier,)
            self._input.write(payload, timeout=self.step_timeout)
        loss = float(self._loss_r.read(timeout=self.step_timeout))
        self._telemetry_tick()
        return loss

    def get_params(self, timeout: float = 60.0) -> list:
        import ray_tpu

        return ray_tpu.get([s.get_params.remote() for s in self.stages],
                           timeout=timeout)

    def close(self, timeout: float = 30.0, kill_actors: bool = False) -> None:
        import ray_tpu

        if self._closed or not self._started:
            self._closed = True
            if kill_actors:
                for s in self.stages:
                    try:
                        ray_tpu.kill(s)
                    except Exception:
                        pass
            return
        self._closed = True
        from ray_tpu.core.api import _global_client

        self._input.close(unlink=True)
        for ref in self._loop_refs:
            try:
                ray_tpu.get(ref, timeout=timeout)
            except Exception:
                pass
        client = _global_client()
        for a, name in self._remote_created:
            try:
                client.direct_request(a, "dag_chan_close", name=name,
                                      unlink=True)
            except Exception:
                pass
        if kill_actors:
            for s in self.stages:
                try:
                    ray_tpu.kill(s)
                except Exception:
                    pass


def eager_pipeline_step(stage_actors, x, y, n_microbatches: int,
                        timeout: float = 120.0) -> float:
    """GPipe over ordinary actor calls — the dynamic-dispatch baseline
    the compiled 1F1B mode is benchmarked against. Every microbatch edge
    pays actor-call submission + result resolution through the task
    plane; returns the step's mean loss."""
    import numpy as np

    import ray_tpu

    stages = list(stage_actors)
    M = int(n_microbatches)
    x, y = np.asarray(x), np.asarray(y)
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by M={M}")
    mb = B // M
    # per-actor sequencing: each stage's ops chain on its previous op so
    # the GPipe order holds even under concurrent actor executors
    last_of: dict = {}

    def call(i, method, *args):
        ref = getattr(stages[i], method).remote(*args,
                                                _after=last_of.get(i))
        last_of[i] = ref
        return ref

    # forward sweep: chain refs stage to stage (dependencies resolve in
    # the workers; the driver still pays per-call dispatch for each edge)
    for k in range(M):
        r = None
        for i in range(len(stages)):
            xk = x[k * mb:(k + 1) * mb] if i == 0 else r
            yk = y[k * mb:(k + 1) * mb] if i == len(stages) - 1 else None
            r = call(i, "fwd_eager", k, xk, yk)
    ray_tpu.get(r, timeout=timeout)
    # backward sweep in reverse microbatch order
    last_done = None
    for k in reversed(range(M)):
        g = None
        for i in reversed(range(len(stages))):
            g = call(i, "bwd_eager", k, g)
        last_done = g
    if last_done is not None:
        ray_tpu.get(last_done, timeout=timeout)
    loss_ref = call(len(stages) - 1, "pop_mean_loss")
    ray_tpu.get([call(i, "apply_grads", M) for i in range(len(stages))],
                timeout=timeout)
    return float(ray_tpu.get(loss_ref, timeout=timeout))
