"""Pipeline parallelism: GPipe-style microbatch pipeline over the `pp` mesh axis.

The reference gets pipeline parallelism two ways (SURVEY.md §2.13): vLLM's
multi-node PP driven through placement groups, and Compiled Graphs
(`python/ray/dag/compiled_dag_node.py`) whose per-actor READ/COMPUTE/WRITE
schedules pipeline NCCL send/recv between stages. The TPU-native answer keeps
the whole pipeline INSIDE one XLA program: stages are a `pp` mesh axis, stage
hand-off is `lax.ppermute` riding the ICI ring, and the schedule is a
`lax.scan` over M + F - 1 ticks — XLA overlaps the permute with the next
tick's compute, no host in the loop.

Design (partial-manual shard_map):
- only `pp` is manual (`axis_names={'pp'}`); dp/fsdp/tp stay auto, so the
  stage function can keep its ordinary sharding annotations and XLA still
  inserts dp gradient allreduces etc.;
- stage params have a leading stage dim sharded over `pp`; each instance
  squeezes its own stage's slice;
- microbatch schedule: at tick t, stage 0 injects microbatch t (t < M), the
  last stage emits microbatch t-(F-1); a final masked `psum` replicates the
  output to every stage so downstream (loss/unembed) code sees a plain
  replicated-over-pp activation.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.mesh import current_mesh
from ray_tpu.util.collective.hierarchy import (account_collective,
                                               ring_perm)
from ray_tpu.utils.jax_compat import shard_map as _compat_shard_map


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    n_microbatches: int,
    mesh=None,
    axis: str = "pp",
) -> jax.Array:
    """Run `stage_fn` as a `pp`-deep pipeline over microbatches of `x`.

    stage_params: pytree whose every leaf has leading dim = pp degree
      (stage-stacked), sharded over `axis`.
    x: [B, ...] activations; B % n_microbatches == 0.
    stage_fn(params_for_one_stage, x_mb) -> x_mb.
    """
    mesh = mesh or current_mesh()
    if mesh is None:
        raise RuntimeError("pipeline_apply needs a mesh (use_mesh or mesh=)")
    F = mesh.shape[axis]
    if F == 1:
        sp = jax.tree.map(lambda a: a[0], stage_params)
        return stage_fn(sp, x)

    M = n_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by n_microbatches {M}")
    if M < F:
        raise ValueError(f"n_microbatches {M} < pipeline depth {F}: "
                         "bubble would dominate; use M >= pp")
    # On CPU only, the shard_map boundary runs in f32: XLA's CPU backend (the
    # dryrun/test platform) miscompiles sub-group bf16 psum in partial-manual
    # regions ("Invalid binary instruction opcode copy" CHECK), and the f32
    # boundary also covers the backward-pass psum of the replicated input's
    # cotangent. On TPU the bug doesn't exist and bf16 boundaries halve the
    # buffer + ICI psum bytes. Compute inside the stages stays in x.dtype.
    compute_dtype = x.dtype
    boundary_dtype = (jnp.float32 if jax.default_backend() == "cpu"
                      else compute_dtype)
    xs = x.reshape(M, B // M, *x.shape[1:]).astype(boundary_dtype)
    if not isinstance(x, jax.core.Tracer):
        # eager entry: account the pipeline's stage hand-off wire bytes
        # ((M+F-1) ticks, each stage forwards one microbatch activation).
        # The ring moves compute_dtype state (spmd_fn casts back before
        # the ppermute) — size it off x, not the f32 boundary buffer.
        mb_bytes = x.nbytes // M
        account_collective("pipeline.ppermute", (M + F - 1) * F * mb_bytes,
                           str(compute_dtype), hop="intra")

    def spmd_fn(stage_p, xs):
        xs = xs.astype(compute_dtype)
        stage_p = jax.tree.map(lambda a: a[0], stage_p)   # this stage's slice
        stage = lax.axis_index(axis)
        state = jnp.zeros_like(xs[0])
        outs = jnp.zeros_like(xs)

        def tick(carry, t):
            state, outs = carry
            # stage 0 injects microbatch t
            inp = lax.dynamic_index_in_dim(xs, jnp.minimum(t, M - 1), 0,
                                           keepdims=False)
            state = jnp.where((stage == 0) & (t < M), inp, state)
            state = stage_fn(stage_p, state)
            # last stage emits microbatch t-(F-1)
            out_t = t - (F - 1)
            idx = jnp.clip(out_t, 0, M - 1)
            cur = lax.dynamic_index_in_dim(outs, idx, 0, keepdims=False)
            new = jnp.where((stage == F - 1) & (out_t >= 0), state, cur)
            outs = lax.dynamic_update_index_in_dim(outs, new, idx, 0)
            # rotate activations one stage forward (ICI ring; the
            # canonical collective-layer ring hop)
            state = lax.ppermute(state, axis, ring_perm(F))
            return (state, outs), None

        (state, outs), _ = lax.scan(tick, (state, outs),
                                    jnp.arange(M + F - 1))
        # replicate the last stage's outputs to every stage (psum in the
        # boundary dtype — see dtype note above)
        outs = outs.astype(boundary_dtype)
        outs = lax.psum(
            jnp.where(stage == F - 1, outs, jnp.zeros_like(outs)), axis)
        return outs

    out = _compat_shard_map(
        spmd_fn,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        axis_names={axis},
        check_vma=False,
    )(stage_params, xs)
    return out.astype(compute_dtype).reshape(B, *x.shape[1:])


def stack_stages(block_params: Any, n_stages: int) -> Any:
    """[L, ...]-stacked block params -> [n_stages, L/n_stages, ...]."""

    def reshape(a):
        L = a.shape[0]
        if L % n_stages:
            raise ValueError(f"{L} layers not divisible by {n_stages} stages")
        return a.reshape(n_stages, L // n_stages, *a.shape[1:])

    return jax.tree.map(reshape, block_params)


def make_stage_fn(block_fn: Callable[[jax.Array, Any], jax.Array],
                  remat: bool = True) -> Callable:
    """Lift a single-block fn (x, block_params) -> x into a stage fn that
    scans its stage's [L/F, ...] blocks."""
    body = jax.checkpoint(block_fn) if remat else block_fn

    def stage_fn(stage_p, x):
        x, _ = lax.scan(lambda c, bp: (body(c, bp), None), x, stage_p)
        return x

    return stage_fn
