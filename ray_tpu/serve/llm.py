"""LLM serving: continuous-batching decode engine on TPU + deployment glue.

Capability counterpart of the reference's serve.llm stack
(`python/ray/llm/_internal/serve/` — vLLM engine behind deployments). The
TPU-native engine is ours: a jitted GPT-2 KV-cache decode step over a fixed
slot batch (ray_tpu/models/gpt2.py decode_step); requests are admitted into
free slots as others finish (continuous batching), so decode throughput
stays at the full batch width under load.

Real weights: `checkpoint=` loads a `gpt2.save_params` directory (what
the trainer writes), so replicas serve trained parameters, not random
init; `tokenizer=` accepts any encode/decode object (an HF tokenizer
adapter is provided, gated on a locally cached vocab — zero egress).
ByteTokenizer remains the self-contained fallback.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional


class ByteTokenizer:
    """utf-8 bytes as token ids (0-255); eos = 0. Self-contained fallback so
    serving works without downloaded vocabularies."""

    eos_id = 0

    def encode(self, text: str) -> List[int]:
        return [b + 1 for b in text.encode("utf-8")][:2048]

    def decode(self, ids: List[int]) -> str:
        # ids beyond the byte range (larger model vocabs) wrap; this is a
        # demo tokenizer, not a real vocabulary
        return bytes((i - 1) % 256 for i in ids if i > 0).decode(
            "utf-8", errors="replace")


class HFTokenizer:
    """transformers tokenizer adapter (reference serve.llm uses the HF
    tokenizer of the served checkpoint). Requires the vocab to already be
    on disk/cache — this environment has no egress, so construction
    fails loudly rather than downloading."""

    def __init__(self, name_or_path: str):
        try:
            from transformers import AutoTokenizer
        except ImportError as e:  # pragma: no cover - env-dependent
            raise ImportError("HFTokenizer requires `transformers`") from e
        self._tok = AutoTokenizer.from_pretrained(name_or_path,
                                                  local_files_only=True)
        self.eos_id = self._tok.eos_token_id or 0

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text)

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids)


class _Request:
    def __init__(self, prompt_ids: List[int], max_tokens: int,
                 temperature: float, top_k: int = 0, top_p: float = 1.0,
                 prefix_future=None, prefix_wait_s: float = 30.0):
        self.prompt_ids = prompt_ids
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        # async prefill fetch: a concurrent.futures.Future resolving to a
        # KV blob (or None). The engine defers THIS request's slot
        # placement until the blob lands — other lanes keep decoding —
        # and falls back to local prefill at the deadline.
        self.prefix_future = prefix_future
        self.prefix_deadline = time.time() + prefix_wait_s
        self.generated: List[int] = []
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.finish_reason: str = "stop"
        # streaming consumers: wakes on every appended token batch
        self.progress = threading.Condition()
        self._sent_text = ""  # cumulative text already shipped to the consumer
        self.t_enqueue = time.time()
        self.t_first: Optional[float] = None   # first generated token (TTFT)


def plan_chunk_budget(pending_lens: List[int], decoding: List[bool],
                      chunk_size: int, budget: int) -> List[int]:
    """Token-budget step plan for one continuous-batching tick: how many
    tokens each slot processes this step.

    Decode slots are reserved FIRST and unconditionally (one token each:
    a prefilling long prompt can never starve running generations), then
    the remaining budget is dealt to prefilling slots in slot order,
    capped at `chunk_size` per slot. When only prefills are live, at
    least one slot always makes progress regardless of budget (no
    livelock on a tiny budget). Pure — unit-tested directly.
    """
    n = len(pending_lens)
    takes = [0] * n
    for i in range(n):
        if decoding[i]:
            takes[i] = 1
            budget -= 1
    any_progress = any(takes)
    for i in range(n):
        if decoding[i] or pending_lens[i] <= 0:
            continue
        take = min(pending_lens[i], chunk_size, max(budget, 0))
        if take <= 0 and not any_progress:
            take = 1      # sole-prefill guarantee
        if take <= 0:
            continue
        takes[i] = take
        budget -= take
        any_progress = True
    return takes


class LLMEngine:
    """Continuous-batching decode engine over a fixed slot batch.

    `scheduler="continuous"` (default) is per-step join/evict with a
    token-budget step plan: new requests enter the running batch at the
    next decode step, finished sequences free their KV slot immediately,
    and long prompts prefill in `prefill_chunk_size`-token chunks
    (gpt2.prefill_chunk) under `max_num_batched_tokens` per step, with
    decode lanes reserved first so prefill can't starve decode.
    `scheduler="fixed"` is the admit-then-run loop kept for the serve
    bench comparison: a batch is admitted only when every slot is free
    and runs token-by-token to completion before the next admit.
    """

    def __init__(self, preset: str = "gpt2-tiny", max_batch: int = 4,
                 max_seq_len: int = 128, seed: int = 0,
                 model_overrides: Optional[dict] = None,
                 checkpoint: Optional[str] = None,
                 tokenizer: Any = None,
                 enable_prefix_caching: bool = True,
                 kv_blocks: int = 64, kv_block_size: int = 16,
                 tensor_parallel_size: int = 1,
                 scheduler: str = "continuous",
                 prefill_chunk_size: int = 16,
                 max_num_batched_tokens: Optional[int] = None,
                 params_override=None, cfg_override=None,
                 weights_id: Optional[str] = None,
                 weight_store: bool = True):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import gpt2

        self.jax, self.jnp, self.gpt2 = jax, jnp, gpt2
        self.tensor_parallel_size = tensor_parallel_size
        overrides = dict(model_overrides or {})
        overrides.setdefault("max_seq_len", max_seq_len)
        if params_override is not None:
            # LoRA-merged (or otherwise prepared) weights from the caller.
            # The architecture must describe THOSE weights: callers that
            # derived them from a checkpoint-loaded base pass the base's
            # resolved cfg (re-deriving from the preset would mismatch
            # when the checkpoint's architecture differs — ADVICE r5).
            self.cfg = (cfg_override if cfg_override is not None
                        else gpt2.GPT2Config.preset(preset, **overrides))
            self.params = params_override
            self.checkpoint = checkpoint
        elif checkpoint:
            # REAL weights: architecture from the checkpoint sidecar,
            # runtime knobs (seq len etc.) from the preset/overrides.
            # Cold start tries the P2P weight plane FIRST — the manifest
            # resolves from the gossiped directory (zero head RPCs) and
            # the leaves stream from peer replicas under a bounded host
            # budget (serve/weight_store.py) — and degrades to the
            # central checkpoint-path read on any miss. The replica that
            # pays the path read publishes the tree back, so the NEXT
            # replica of this model pulls from peers.
            import time as _time

            base = gpt2.GPT2Config.preset(preset, **overrides)
            self.params = None
            t0 = _time.perf_counter()
            if weight_store:
                try:
                    from ray_tpu.serve import weight_store as _ws

                    store = _ws.get_store()
                    loaded = (store.load_params(checkpoint, base_cfg=base)
                              if store is not None else None)
                    if loaded is not None:
                        self.params, self.cfg = loaded
                        _ws.observe_cold_start(
                            _time.perf_counter() - t0, "p2p")
                except Exception:
                    self.params = None   # never fail init on the store
            if self.params is None:
                self.params, self.cfg = gpt2.load_params(checkpoint,
                                                         cfg=base)
                if weight_store:
                    from ray_tpu.serve import weight_store as _ws

                    _ws.observe_cold_start(
                        _time.perf_counter() - t0, "checkpoint")
                    _ws.maybe_publish_params_async(
                        self.params, checkpoint,
                        arch={k: getattr(self.cfg, k)
                              for k in gpt2._CFG_FIELDS})
            self.checkpoint = checkpoint
        else:
            self.cfg = gpt2.GPT2Config.preset(preset, **overrides)
            self.params = gpt2.init_params(jax.random.key(seed), self.cfg)
            self.checkpoint = None
        # weight identity for the cluster prefix store: engines whose KV
        # is interchangeable must agree on it. Checkpoint path or
        # preset+seed derive it; params_override callers (LoRA adapters)
        # pass the BASE engine's id explicitly so adapters share
        # base-model prefix entries — an override without one gets a
        # unique id, which can never collide into a wrong-KV hit.
        if weights_id is not None:
            self.weights_id = weights_id
        elif params_override is not None:
            import uuid

            self.weights_id = f"override-{uuid.uuid4().hex[:12]}"
        else:
            self.weights_id = checkpoint or f"{preset}@seed{seed}"
        self.max_batch = max_batch
        # serving window: the caller's bound caps KV-cache memory even
        # when a checkpoint's architecture allows a longer context (the
        # sidecar must win for PARAM shapes, never for cache sizing)
        self.max_seq_len = min(max_seq_len, self.cfg.max_seq_len)
        self.cache = gpt2.init_cache(self.cfg, max_batch, self.max_seq_len)
        cfg = self.cfg
        # paged prefix cache: shared-prompt requests skip prefill for the
        # cached span (reference: vLLM prefix caching behind serve.llm)
        self.kv = None
        if enable_prefix_caching:
            from ray_tpu.serve.kv_cache import PagedKVCache

            self.kv = PagedKVCache(cfg.n_layer, cfg.n_head, cfg.head_dim,
                                   num_blocks=kv_blocks,
                                   block_size=kv_block_size,
                                   dtype=cfg.dtype)

        self.scheduler = scheduler
        # chunk must fit the serving window (prefill_chunk requires C <= T)
        self.prefill_chunk_size = max(1, min(prefill_chunk_size,
                                             self.max_seq_len - 1))
        self.max_num_batched_tokens = (
            max_num_batched_tokens if max_num_batched_tokens
            else max(2 * max_batch, max_batch + self.prefill_chunk_size))

        def _step(params, cache, tokens, pos, active):
            return gpt2.decode_step(params, cache, tokens, pos, active, cfg)

        def _chunk(params, cache, tokens, pos0, length, active):
            return gpt2.prefill_chunk(params, cache, tokens, pos0, length,
                                      active, cfg)

        if tensor_parallel_size > 1:
            # TP-sharded engine (reference: vLLM TP workers in a
            # STRICT_PACK PG, `server_models.py:443-461`) — here TP is a
            # mesh axis: params shard by their logical axes, the KV cache
            # shards over heads, XLA inserts the ICI collectives. One
            # process drives all chips (single-controller SPMD).
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ray_tpu.parallel.mesh import (MeshConfig, build_mesh,
                                               use_mesh)

            mesh = build_mesh(
                MeshConfig(tp=tensor_parallel_size),
                devices=jax.devices()[:tensor_parallel_size])
            self.mesh = mesh
            with use_mesh(mesh):
                pspecs = gpt2.param_specs(cfg)
            param_sh = jax.tree.map(
                lambda s: NamedSharding(mesh, s), pspecs)
            self.params = jax.tree.map(jax.device_put, self.params,
                                       param_sh)
            # KV cache [L, B, H, T, Dh]: shard attention heads over tp
            cache_sh = NamedSharding(mesh, P(None, None, "tp", None, None))
            self.cache = jax.tree.map(
                lambda a: jax.device_put(a, cache_sh), self.cache)
            rep = NamedSharding(mesh, P())
            self._step = jax.jit(
                _step, donate_argnums=(1,),
                in_shardings=(param_sh, {"k": cache_sh, "v": cache_sh},
                              rep, rep, rep),
                out_shardings=(rep, {"k": cache_sh, "v": cache_sh}))
            self._chunk_step = jax.jit(
                _chunk, donate_argnums=(1,),
                in_shardings=(param_sh, {"k": cache_sh, "v": cache_sh},
                              rep, rep, rep, rep),
                out_shardings=(rep, {"k": cache_sh, "v": cache_sh}))
        else:
            self.mesh = None
            self._step = jax.jit(_step, donate_argnums=(1,))
            self._chunk_step = jax.jit(_chunk, donate_argnums=(1,))
        if self.scheduler == "fixed":
            self._chunk_step = None   # legacy admit-then-run, 1 token/step
        self.tokenizer = tokenizer if tokenizer is not None else ByteTokenizer()

        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._streams: Dict[str, tuple] = {}   # sid -> (request, last_access)
        self._slots: List[Optional[_Request]] = [None] * max_batch
        self._slot_pos = [0] * max_batch
        self._slot_prefill: List[List[int]] = [[] for _ in range(max_batch)]
        # async prefill fetch: requests whose KV blob is still in flight
        # park here (other lanes keep decoding); resolved ones re-enter
        # admission ahead of the queue
        self._deferred: List[_Request] = []
        self._ready: List[_Request] = []
        self._stop = threading.Event()
        self._stats_lock = threading.Lock()
        self.total_generated = 0
        self.engine_steps = 0          # jitted step calls (either kind)
        self.chunk_steps = 0           # steps that ran the chunked program
        self.tokens_prefilled = 0      # prompt tokens processed
        self.prefix_imports = 0        # deferred blobs installed
        self.prefix_blocks_imported = 0
        self.prefix_wait_timeouts = 0  # deadline hit: local prefill
        self.ttft_sum = 0.0            # submit -> first generated token
        self.ttft_count = 0
        self.last_ttft_s = 0.0
        # callables other threads need run ON the engine thread (the KV
        # pool is engine-owned, unlocked state: exports must not race
        # _alloc's block eviction/reuse)
        self._engine_calls: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(target=self._engine_loop, daemon=True,
                                        name="llm-engine")
        self._thread.start()

    # ------------------------------------------------------------- public
    @property
    def prefix_model_key(self) -> Optional[str]:
        """Cluster prefix-store key: engines with interchangeable KV
        (same weights + cache geometry) agree; anything else differs."""
        if self.kv is None:
            return None
        from ray_tpu.serve.prefix_store import model_cache_key

        cfg = self.cfg
        return model_cache_key(self.weights_id, cfg.n_layer, cfg.n_head,
                               cfg.head_dim, self.jnp.dtype(cfg.dtype).name,
                               self.kv.block_size)

    def generate(self, prompt: str = "", prompt_ids: Optional[List[int]] = None,
                 max_tokens: int = 16, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0,
                 timeout: float = 120.0, prefix_future=None,
                 prefix_wait_s: float = 30.0) -> Dict[str, Any]:
        req = self._make_request(prompt, prompt_ids, max_tokens,
                                 temperature, top_k, top_p,
                                 prefix_future=prefix_future,
                                 prefix_wait_s=prefix_wait_s)
        ids = req.prompt_ids
        self._queue.put(req)
        if not req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if req.error:
            raise RuntimeError(req.error)
        return {"token_ids": req.generated,
                "text": self.tokenizer.decode(req.generated),
                "prompt_tokens": len(ids),
                "completion_tokens": len(req.generated)}

    # ----------------------------------------------------------- streaming
    def _make_request(self, prompt, prompt_ids, max_tokens, temperature,
                      top_k, top_p, prefix_future=None,
                      prefix_wait_s: float = 30.0) -> "_Request":
        ids = prompt_ids if prompt_ids is not None else \
            self.tokenizer.encode(prompt)
        ids = ids or [self.tokenizer.eos_id]
        ids = ids[-(self.max_seq_len - 2):]
        budget = self.max_seq_len - len(ids) - 1
        return _Request(ids, max(0, min(max_tokens, budget)), temperature,
                        top_k=top_k, top_p=top_p,
                        prefix_future=prefix_future,
                        prefix_wait_s=prefix_wait_s)

    def start_stream(self, prompt: str = "",
                     prompt_ids: Optional[List[int]] = None,
                     max_tokens: int = 16, temperature: float = 0.0,
                     top_k: int = 0, top_p: float = 1.0,
                     prefix_future=None,
                     prefix_wait_s: float = 30.0) -> str:
        """Admit a request for incremental consumption via stream_next
        (the engine path behind OpenAI `stream: true`)."""
        import uuid

        req = self._make_request(prompt, prompt_ids, max_tokens,
                                 temperature, top_k, top_p,
                                 prefix_future=prefix_future,
                                 prefix_wait_s=prefix_wait_s)
        sid = uuid.uuid4().hex
        self._streams[sid] = (req, time.time())
        self._queue.put(req)
        return sid

    def stream_next(self, stream_id: str, cursor: int = 0,
                    timeout: float = 1.0) -> Dict[str, Any]:
        """Tokens generated beyond `cursor`. Waits briefly (bounded: a
        long block would pin a replica actor thread per queued stream
        and starve health checks); an empty delta means "poll again".
        `text` is the CUMULATIVE decode — a per-batch decode would split
        multi-byte characters across chunk boundaries; consumers diff
        against their previous cumulative text. The stream entry is
        dropped once the consumer has read to the end."""
        ent = self._streams.get(stream_id)
        if ent is None:
            raise KeyError(f"unknown stream {stream_id}")
        req, _ = ent
        self._streams[stream_id] = (req, time.time())
        deadline = time.time() + timeout
        with req.progress:
            while (len(req.generated) <= cursor and not req.done.is_set()
                   and req.error is None):
                left = deadline - time.time()
                if left <= 0:
                    break
                req.progress.wait(left)
        if req.error:
            self._streams.pop(stream_id, None)
            return {"error": req.error, "done": True, "token_ids": [],
                    "text": "", "cursor": cursor}
        new = req.generated[cursor:]
        done = req.done.is_set() and cursor + len(new) >= len(req.generated)
        if done:
            self._streams.pop(stream_id, None)
        # delta computed HERE from the cumulative decode (multi-byte
        # characters must not split across chunk boundaries), decoded
        # only when tokens actually advanced — no per-poll O(L) work and
        # no cumulative string shipped per RPC
        delta = ""
        if new or done:
            full = self.tokenizer.decode(req.generated[:cursor + len(new)])
            if not done and full.endswith("\ufffd"):
                # trailing partial multi-byte sequence: hold it back until
                # its continuation bytes arrive
                full = full[:-1]
            delta = (full[len(req._sent_text):]
                     if full.startswith(req._sent_text) else full)
            req._sent_text = full
        return {"token_ids": new, "text": delta,
                "done": done, "cursor": cursor + len(new),
                "finish_reason": req.finish_reason if done else None}

    # --------------------------------------------- KV transfer (prefill/decode)
    def export_prefix(self, prompt: str = "",
                      prompt_ids: Optional[List[int]] = None):
        """Disaggregated serving, prefill side: run (or reuse) the
        prompt's prefill, then hand back a host blob of its pooled KV
        blocks for a DECODE engine to import (reference KV-transfer
        connectors: nixl/lmcache behind serve.llm)."""
        if self.kv is None:
            raise RuntimeError("prefix caching disabled: no KV to export")
        ids = prompt_ids if prompt_ids is not None else \
            self.tokenizer.encode(prompt)
        ids = ids[-(self.max_seq_len - 2):]
        blob = self.export_pooled(ids[:-1])
        if blob is None or len(blob["ids"]) < len(ids) - 1 - \
                (len(ids) - 1) % self.kv.block_size:
            # not pooled yet: run the prefill (generate 1 token) which
            # publishes the prompt's blocks, then export
            self.generate(prompt_ids=ids, max_tokens=1)
            blob = self.export_pooled(ids[:-1])
        return blob

    def export_pooled(self, ids: List[int], timeout: float = 30.0):
        """Export `ids`' pooled KV blocks ON the engine thread. The pool
        is unlocked engine-owned state: an export racing `_alloc`'s block
        eviction/reuse could copy another request's bytes under this
        prompt's content hash, so off-thread callers marshal through the
        engine-call queue. Falls back to a direct (pre-PR-13-semantics)
        export if the engine thread is wedged past `timeout`."""
        from ray_tpu.serve.kv_cache import export_prefix as _export

        if (threading.current_thread() is self._thread
                or not self._thread.is_alive()):
            return _export(self.kv, ids)
        from concurrent.futures import Future
        from concurrent.futures import TimeoutError as _FutTimeout

        fut: Future = Future()

        def _do():
            try:
                fut.set_result(_export(self.kv, list(ids)))
            except BaseException as e:   # engine thread must survive
                fut.set_exception(e)

        self._engine_calls.put(_do)
        try:
            return fut.result(timeout=timeout)
        except _FutTimeout:
            return _export(self.kv, list(ids))

    def import_prefix(self, blob) -> int:
        """Decode side: install a prefill replica's exported KV blocks;
        subsequent matching prompts skip prefill for the covered span."""
        if self.kv is None:
            raise RuntimeError("prefix caching disabled: no KV to import")
        from ray_tpu.serve.kv_cache import import_prefix as _import

        return _import(self.kv, blob)

    def shutdown(self):
        self._stop.set()

    # ------------------------------------------------------------- engine
    def _admit(self):
        self._admit_deferred()
        if self.scheduler == "fixed":
            # admit-then-run: a new batch forms only once EVERY slot is
            # free (the seed loop the continuous scheduler replaces; kept
            # for the serve bench A/B)
            if any(r is not None for r in self._slots):
                return
        for i in range(self.max_batch):
            if self._slots[i] is None:
                req = self._next_ready()
                if req is None:
                    return
                self._place(i, req)

    def _next_ready(self) -> Optional[_Request]:
        """Next admittable request: resolved deferred requests first,
        then the queue. A queued request whose KV blob fetch is still in
        flight parks in `_deferred` (its slot goes to the next request —
        other lanes decode while the blob crosses the network) instead
        of blocking admission."""
        while True:
            if self._ready:
                return self._ready.pop(0)
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return None
            fut = req.prefix_future
            if fut is not None and not fut.done() \
                    and time.time() < req.prefix_deadline:
                self._deferred.append(req)
                continue
            self._resolve_prefix(req)
            return req

    def _admit_deferred(self) -> None:
        """Re-admit parked requests whose blob landed (import happens
        HERE, on the engine thread — the KV pool is engine-owned state)
        or whose wait deadline passed (degrade to local prefill)."""
        if not self._deferred:
            return
        now = time.time()
        still: List[_Request] = []
        for req in self._deferred:
            fut = req.prefix_future
            if fut is not None and not fut.done() \
                    and now < req.prefix_deadline:
                still.append(req)
                continue
            self._resolve_prefix(req)
            self._ready.append(req)
        self._deferred = still

    def _resolve_prefix(self, req: _Request) -> None:
        fut, req.prefix_future = req.prefix_future, None
        if fut is None:
            return
        blob = None
        if fut.done():
            try:
                blob = fut.result()
            except Exception:
                blob = None
        else:
            fut.cancel()    # deadline passed: prefill locally instead
            self.prefix_wait_timeouts += 1
        if blob and self.kv is not None:
            try:
                installed = self.import_prefix(blob)
                self.prefix_imports += 1
                self.prefix_blocks_imported += installed
            except Exception:
                pass        # bad blob: local prefill is always correct

    def _place(self, i: int, req: _Request) -> None:
        self._slots[i] = req
        self._slot_pos[i] = 0
        self._slot_prefill[i] = list(req.prompt_ids)
        if self.kv is not None and len(req.prompt_ids) > 1:
            # the last prompt token is always re-run (its logits
            # seed generation), so match against ids[:-1]
            n_hit, blocks = self.kv.match_prefix(
                req.prompt_ids[:-1])
            if n_hit:
                self.cache = self.kv.copy_into_slot(
                    self.cache, i, blocks)
                self._slot_pos[i] = n_hit
                self._slot_prefill[i] = list(
                    req.prompt_ids[n_hit:])

    def _sweep_streams(self) -> None:
        """Expire abandoned stream entries (client vanished): the sweep
        must not depend on some OTHER stream being polled."""
        now = time.time()
        for sid, (r, ts) in list(self._streams.items()):
            if r.done.is_set() and now - ts > 300:
                self._streams.pop(sid, None)

    def _engine_loop(self):
        import numpy as np

        rng = np.random.default_rng(0)
        last_sweep = time.time()
        while not self._stop.is_set():
            if time.time() - last_sweep > 60:
                last_sweep = time.time()
                self._sweep_streams()
            # marshalled work (KV exports) runs between steps: the pool
            # can't mutate under an export that shares this thread
            for _ in range(8):
                try:
                    fn = self._engine_calls.get_nowait()
                except queue.Empty:
                    break
                try:
                    fn()
                except Exception:
                    pass
            self._admit()
            live = [i for i, r in enumerate(self._slots) if r is not None]
            if not live:
                time.sleep(0.005)
                continue
            prefilling = any(self._slot_prefill[i] for i in live)
            if prefilling and self._chunk_step is not None:
                self._run_chunk_step(live, rng, np)
            else:
                self._run_decode_step(live, rng, np)

    def _run_decode_step(self, live, rng, np):
        """One single-token step for every live slot (the pure-decode fast
        path; also the only step the fixed scheduler ever runs)."""
        jnp = self.jnp
        tokens = np.zeros((self.max_batch,), np.int32)
        pos = np.asarray(self._slot_pos, np.int32)
        active = np.zeros((self.max_batch,), bool)
        for i in live:
            active[i] = True
            if self._slot_prefill[i]:
                tokens[i] = self._slot_prefill[i][0]
            else:
                tokens[i] = (self._slots[i].generated[-1]
                             if self._slots[i].generated
                             else self._slots[i].prompt_ids[-1])
        logits, self.cache = self._step(
            self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(pos), jnp.asarray(active))
        logits = np.asarray(logits)
        self.engine_steps += 1
        for i in live:
            req = self._slots[i]
            self._slot_pos[i] += 1
            if self._slot_prefill[i]:
                self._slot_prefill[i].pop(0)
                self.tokens_prefilled += 1
                if self._slot_prefill[i]:
                    continue  # still prefilling; ignore logits
                if self.kv is not None:
                    # prompt fully resident in this slot's cache:
                    # publish its full blocks for future prefix hits
                    # (dedup'd: shared prefixes stored once)
                    self.kv.store_prefix(req.prompt_ids, self.cache, i)
            self._finish_token(i, req, logits[i], rng, np)

    def _run_chunk_step(self, live, rng, np):
        """One token-budget step: decode slots advance one token each
        (reserved first), prefilling slots consume up to a chunk of their
        remaining prompt — all in ONE fused prefill_chunk call."""
        jnp = self.jnp
        B, C = self.max_batch, self.prefill_chunk_size
        pending = [len(self._slot_prefill[i]) if self._slots[i] is not None
                   else 0 for i in range(B)]
        decoding = [self._slots[i] is not None and not self._slot_prefill[i]
                    for i in range(B)]
        takes = plan_chunk_budget(pending, decoding, C,
                                  self.max_num_batched_tokens)
        tokens = np.zeros((B, C), np.int32)
        lengths = np.zeros((B,), np.int32)
        for i in live:
            take = takes[i]
            if take <= 0:
                continue
            # never step past the serving window (prefill_chunk requires
            # pos0 + length <= T; _make_request already bounds prompts)
            take = min(take, self.max_seq_len - self._slot_pos[i])
            if take <= 0:
                continue
            lengths[i] = take
            if self._slot_prefill[i]:
                tokens[i, :take] = self._slot_prefill[i][:take]
            else:
                req = self._slots[i]
                tokens[i, 0] = (req.generated[-1] if req.generated
                                else req.prompt_ids[-1])
        active = lengths > 0
        if not active.any():
            time.sleep(0.001)
            return
        logits, self.cache = self._chunk_step(
            self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(np.asarray(self._slot_pos, np.int32)),
            jnp.asarray(lengths), jnp.asarray(active))
        logits = np.asarray(logits)
        self.engine_steps += 1
        self.chunk_steps += 1
        for i in live:
            take = int(lengths[i])
            if take <= 0:
                continue
            req = self._slots[i]
            self._slot_pos[i] += take
            if self._slot_prefill[i]:
                del self._slot_prefill[i][:take]
                self.tokens_prefilled += take
                if self._slot_prefill[i]:
                    continue  # chunk didn't cover the prompt yet
                if self.kv is not None:
                    self.kv.store_prefix(req.prompt_ids, self.cache, i)
            # the chunk ended at the prompt's final token (or a decode
            # lane): its last-position logits seed/continue generation
            self._finish_token(i, req, logits[i], rng, np)

    def _finish_token(self, i, req, logit_row, rng, np):
        """Sample one token from `logit_row`, append it, and evict the
        slot the moment the request finishes (its KV slot frees for the
        next admit — same tick)."""
        if req.temperature > 0:
            lg = logit_row / req.temperature
            if req.top_k and req.top_k < len(lg):
                kth = np.partition(lg, -req.top_k)[-req.top_k]
                lg = np.where(lg < kth, -np.inf, lg)
            p = np.exp(lg - lg.max())
            p /= p.sum()
            if req.top_p < 1.0:
                order = np.argsort(p)[::-1]
                # standard nucleus: smallest set whose mass reaches
                # top_p — keep a token if the mass BEFORE it is
                # still short of the threshold (inclusive of the
                # one that crosses it)
                csum = np.cumsum(p[order])
                keep = (csum - p[order]) < req.top_p
                mask = np.zeros_like(p, bool)
                mask[order[keep]] = True
                p = np.where(mask, p, 0.0)
                p /= p.sum()
            nxt = int(rng.choice(len(p), p=p))
        else:
            nxt = int(np.argmax(logit_row))
        if req.t_first is None:
            req.t_first = time.time()
            with self._stats_lock:
                self.last_ttft_s = req.t_first - req.t_enqueue
                self.ttft_sum += self.last_ttft_s
                self.ttft_count += 1
        req.generated.append(nxt)
        self.total_generated += 1
        finished = (len(req.generated) >= req.max_tokens
                    or nxt == self.tokenizer.eos_id
                    or self._slot_pos[i] >= self.max_seq_len - 1)
        if finished:
            req.finish_reason = ("stop" if nxt == self.tokenizer.eos_id
                                 else "length")
            self._slots[i] = None
            req.done.set()
        with req.progress:
            req.progress.notify_all()

    def engine_stats(self) -> dict:
        with self._stats_lock:
            ttft_avg = (self.ttft_sum / self.ttft_count
                        if self.ttft_count else 0.0)
            last_ttft = self.last_ttft_s
        return {"scheduler": self.scheduler,
                "max_batch": self.max_batch,
                "prefill_chunk_size": self.prefill_chunk_size,
                "max_num_batched_tokens": self.max_num_batched_tokens,
                "total_generated": self.total_generated,
                "engine_steps": self.engine_steps,
                "chunk_steps": self.chunk_steps,
                "tokens_prefilled": self.tokens_prefilled,
                "prefix_imports": self.prefix_imports,
                "prefix_blocks_imported": self.prefix_blocks_imported,
                "prefix_wait_timeouts": self.prefix_wait_timeouts,
                "queued": self._queue.qsize(),
                "deferred": len(self._deferred),
                "slots_busy": sum(r is not None for r in self._slots),
                "ttft_avg_s": round(ttft_avg, 6),
                "last_ttft_s": round(last_ttft, 6)}


class LLMServer:
    """Deployment callable: OpenAI-completions-shaped request handling."""

    def __init__(self, preset: str = "gpt2-tiny", max_batch: int = 4,
                 max_seq_len: int = 128, model_overrides: Optional[dict] = None,
                 checkpoint: Optional[str] = None, tokenizer: Any = None,
                 cluster_prefix_cache: bool = False,
                 **engine_kwargs):
        self.engine = LLMEngine(preset=preset, max_batch=max_batch,
                                max_seq_len=max_seq_len,
                                model_overrides=model_overrides,
                                checkpoint=checkpoint, tokenizer=tokenizer,
                                **engine_kwargs)
        # cluster prefix tier: any replica warm-starts from prefixes
        # computed anywhere in the cluster (serve/prefix_store.py)
        self.prefix_store = None
        if cluster_prefix_cache and self.engine.kv is not None:
            from ray_tpu.serve import prefix_store as _ps

            self.prefix_store = _ps.store_for_engine(self.engine)
        self._prefix_pool = None
        self._prefix_pool_lock = threading.Lock()
        self._chain_pool = None
        import uuid

        # distinguishes replicas when a caller aggregates stats() rows
        # sampled through a load-balanced handle
        self.server_id = uuid.uuid4().hex[:12]

    # ------------------------------------------------- cluster prefix tier
    def _prefix_submit(self, fn, *args):
        if self._prefix_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            with self._prefix_pool_lock:
                if self._prefix_pool is None:
                    self._prefix_pool = ThreadPoolExecutor(
                        max_workers=2, thread_name_prefix="prefix-fetch")
        return self._prefix_pool.submit(fn, *args)

    def _warm_start_future(self, eng: "LLMEngine", ids: List[int],
                           tenant: str = "base"):
        """Residency-tier fall-through for one prompt: local engine pool
        (peek, no fetch wins below a block of gain) -> cluster store
        lookup (zero RPCs, cached directory) -> background data-plane
        fetch whose future the engine imports while other lanes decode.
        Returns the blob future, or None when nothing beats local."""
        store = self.prefix_store
        if store is None or eng.kv is None or len(ids) < 2:
            return None
        need = ids[:-1]
        covered = eng.kv.peek_prefix_len(need)
        if len(need) - covered < eng.kv.block_size:
            return None
        hit = store.lookup(need, tenant=tenant)
        if hit is None or hit["n"] <= covered:
            return None
        return self._prefix_submit(store.fetch, hit, tenant)

    def _publish_prefix(self, eng: "LLMEngine", ids: List[int]) -> None:
        """After a completed generation the prompt's blocks are pooled:
        announce them so any OTHER replica can warm-start (dedup'd —
        shared prefixes are stored once cluster-wide). Runs on the
        prefetch executor — the export's device->host copy + seal +
        announce must not be charged to the response's tail latency."""
        store = self.prefix_store
        if store is None or eng.kv is None or len(ids) < 2:
            return
        self._prefix_submit(self._publish_prefix_sync, store, eng, ids)

    @staticmethod
    def _publish_prefix_sync(store, eng: "LLMEngine", ids: List[int]) -> None:
        try:
            store.maybe_publish(eng.kv, ids[:-1],
                                exporter=eng.export_pooled)
        except Exception:
            pass   # publication is an optimization, never a failure path

    def _request_ids(self, eng: "LLMEngine", body: dict,
                     prompt: str = "") -> List[int]:
        ids = body.get("prompt_ids")
        if ids is None:
            ids = eng.tokenizer.encode(prompt or body.get("prompt", ""))
        ids = ids or [eng.tokenizer.eos_id]
        return ids[-(eng.max_seq_len - 2):]

    def __call__(self, request: Any) -> dict:
        body = request if isinstance(request, dict) else getattr(
            request, "json", None) or {}
        ids = self._request_ids(self.engine, body)
        out = self.engine.generate(
            prompt_ids=ids,
            max_tokens=int(body.get("max_tokens", 16)),
            temperature=float(body.get("temperature", 0.0)),
            prefix_future=self._warm_start_future(self.engine, ids))
        self._publish_prefix(self.engine, ids)
        return {
            "object": "text_completion",
            "choices": [{"text": out["text"], "index": 0,
                         "token_ids": out["token_ids"],
                         "finish_reason": "length"}],
            "usage": {"completion_tokens": len(out["token_ids"])},
        }

    def batch_call(self, requests: list) -> list:
        """Compiled-chain batch entry: admit every request of a ring
        entry to the engine CONCURRENTLY, so the continuous-batching
        scheduler joins them into shared steps — a sequential map here
        would silently serialize the engine and forfeit batching on the
        compiled path. Per-item failures come back as chain error
        markers (user errors never fail batch neighbours)."""
        from concurrent.futures import ThreadPoolExecutor

        from ray_tpu.serve.compiled_chain import CHAIN_ERR

        if self._chain_pool is None:
            with self._prefix_pool_lock:
                if self._chain_pool is None:
                    self._chain_pool = ThreadPoolExecutor(
                        max_workers=max(8, self.engine.max_batch),
                        thread_name_prefix="chain-batch")
        futs = [self._chain_pool.submit(self, r) for r in requests]
        out = []
        for f in futs:
            try:
                out.append(f.result())
            except Exception as e:
                out.append({CHAIN_ERR: repr(e), "infra": False})
        return out

    def stream_next(self, stream_id: str, cursor: int = 0) -> dict:
        """Incremental tokens for an SSE stream (proxy-driven pull)."""
        return self.engine.stream_next(stream_id, cursor=cursor)

    def stats(self) -> dict:
        out = self.engine.engine_stats()
        out["server_id"] = self.server_id
        if self.engine.kv is not None:
            out["kv_cache"] = self.engine.kv.stats()
        if self.prefix_store is not None:
            out["prefix_store"] = self.prefix_store.stats()
        return out

    def check_health(self):
        if not self.engine._thread.is_alive():
            raise RuntimeError("engine loop died")


class OpenAIServer(LLMServer):
    """OpenAI-compatible API surface (reference: serve.llm router
    `llm/_internal/serve/deployments/routers/router.py` — /v1/completions,
    /v1/chat/completions, /v1/models). Mount with route_prefix="/v1"."""

    def __init__(self, model_id: str = "ray-tpu-llm",
                 lora_root: Optional[str] = None, max_loras: int = 2,
                 **kwargs):
        super().__init__(**kwargs)
        self.model_id = model_id
        # LoRA multiplexing (reference: multi-LoRA serve.llm deployments;
        # replica-granular here): request `model` = "<base>:<adapter>"
        # resolves {lora_root}/{adapter}.npz, merged into the base params
        # and served by a per-adapter engine under an LRU cap
        self.lora_root = lora_root
        self.max_loras = max_loras
        self._lora_engines: "OrderedDict[str, LLMEngine]" = OrderedDict()
        self._engine_kwargs = dict(kwargs)
        # sid -> (engine, prompt_ids): the ids publish the prompt's
        # prefix into the cluster store when the stream completes
        self._stream_owner: Dict[str, tuple] = {}

    def loaded_lora_ids(self):
        return list(self._lora_engines)

    def _tenant_of(self, body: dict) -> str:
        """Adapter id of the request (`model="<base>:<adapter>"`), or
        "base" — the per-tenant tag on prefix-store hit counters."""
        model = (body or {}).get("model")
        if model and ":" in str(model):
            return str(model).rsplit(":", 1)[1]
        return "base"

    def _engine_for(self, body: dict) -> "LLMEngine":
        model = (body or {}).get("model")
        if (not self.lora_root or not model or model == self.model_id
                or ":" not in str(model)):
            return self.engine
        adapter_id = str(model).rsplit(":", 1)[1]
        eng = self._lora_engines.get(adapter_id)
        if eng is not None:
            self._lora_engines.move_to_end(adapter_id)
            return eng
        from ray_tpu.models.gpt2 import apply_lora, load_lora_npz
        from ray_tpu.serve import weight_store as _ws
        from ray_tpu.utils import fs as _lfs

        # hot-swap path: adapter deltas are first-class weight-plane
        # objects — the first replica to load an adapter publishes it,
        # every later replica pulls it P2P instead of touching lora_root
        # (byte-identical merge: the delta arrays are the same bytes).
        # Any miss falls back to the adapter npz on disk, then publishes.
        adapter = None
        store = _ws.get_store()
        akey = _ws.adapter_store_key(self.engine.weights_id, adapter_id)
        if store is not None:
            try:
                adapter = store.fetch_adapter(akey, tenant=adapter_id)
            except Exception:
                adapter = None
        if adapter is None:
            path = _lfs.join(self.lora_root, f"{adapter_id}.npz")
            adapter = load_lora_npz(path)
            if store is not None:
                try:
                    store.publish_adapter(akey, adapter)
                except Exception:
                    pass
        merged = apply_lora(self.engine.params, adapter)
        kwargs = dict(self._engine_kwargs)
        kwargs.pop("checkpoint", None)
        kwargs.pop("cluster_prefix_cache", None)
        # the merged params have the BASE engine's architecture (which may
        # come from a checkpoint sidecar, not the preset): hand its
        # resolved cfg over instead of re-deriving from the preset.
        # weights_id is the BASE's: adapters share base-model prefix
        # entries in the cluster store (one blob per prefix, hits
        # counted per adapter). DELIBERATE approximation: an adapter
        # whose LoRA retargets attention projections produces slightly
        # different prefix KV than the base — sharing trades that
        # deviation for cluster-wide TTFT, the same trade cross-adapter
        # prompt caches make. Tenants needing exact per-adapter KV pass
        # their own weights_id through engine kwargs to opt out.
        eng = LLMEngine(params_override=merged,
                        cfg_override=self.engine.cfg,
                        weights_id=self.engine.weights_id, **kwargs)
        while len(self._lora_engines) >= self.max_loras:
            _, old = self._lora_engines.popitem(last=False)
            old.shutdown()   # LRU eviction must stop the engine thread
        self._lora_engines[adapter_id] = eng
        return eng

    def stream_next(self, stream_id: str, cursor: int = 0) -> dict:
        eng, ids = self._stream_owner.get(stream_id, (self.engine, None))
        try:
            out = eng.stream_next(stream_id, cursor=cursor)
        except KeyError:
            self._stream_owner.pop(stream_id, None)   # expired engine-side
            raise
        if out.get("done"):
            self._stream_owner.pop(stream_id, None)
            # stream-heavy deployments must feed the cluster store too:
            # the prompt's blocks are pooled once the request finishes
            if ids is not None and not out.get("error"):
                self._publish_prefix(eng, ids)
        return out

    def _note_stream(self, sid: str, eng, ids=None) -> None:
        # abandoned SSE clients leave entries behind; bound the map (the
        # engines sweep their own stream state independently)
        if len(self._stream_owner) > 1024:
            for k in list(self._stream_owner)[:512]:
                self._stream_owner.pop(k, None)
        self._stream_owner[sid] = (eng, ids)

    def __call__(self, request: Any) -> dict:
        path = getattr(request, "path", "/v1/completions")
        if path.endswith("/models"):
            data = [{"id": self.model_id, "object": "model",
                     "owned_by": "ray_tpu"}]
            data += [{"id": f"{self.model_id}:{a}", "object": "model",
                      "owned_by": "ray_tpu", "parent": self.model_id}
                     for a in self.loaded_lora_ids()]
            return {"object": "list", "data": data}
        body = request if isinstance(request, dict) else \
            getattr(request, "json", None) or {}
        max_tokens = int(body.get("max_tokens", 16))
        temperature = float(body.get("temperature", 1.0))
        top_p = float(body.get("top_p", 1.0))
        top_k = int(body.get("top_k", 0))
        stream = bool(body.get("stream"))
        eng = self._engine_for(body)
        # multi-tenant prefix sharing: all adapter engines key the store
        # by the BASE weights, so a system prompt prefilled under one
        # adapter warm-starts every other; hits are counted per tenant
        tenant = self._tenant_of(body)
        if path.endswith("/chat/completions"):
            msgs = body.get("messages", [])
            prompt = "".join(f"<|{m.get('role', 'user')}|>{m.get('content', '')}"
                             for m in msgs) + "<|assistant|>"
            ids = self._request_ids(eng, {}, prompt)
            fut = self._warm_start_future(eng, ids, tenant=tenant)
            if stream:
                sid = eng.start_stream(
                    prompt_ids=ids, max_tokens=max_tokens,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    prefix_future=fut)
                self._note_stream(sid, eng, ids)
                return {"__sse_stream__": {"stream_id": sid,
                                           "model": self.model_id,
                                           "mode": "chat"}}
            out = eng.generate(prompt_ids=ids, max_tokens=max_tokens,
                               temperature=temperature, top_k=top_k,
                               top_p=top_p, prefix_future=fut)
            self._publish_prefix(eng, ids)
            finish = ("length" if out["completion_tokens"] >= max_tokens
                      else "stop")
            return {
                "id": f"chatcmpl-{int(time.time() * 1e3)}",
                "object": "chat.completion", "model": self.model_id,
                "choices": [{"index": 0,
                             "message": {"role": "assistant",
                                         "content": out["text"]},
                             "finish_reason": finish}],
                "usage": {"prompt_tokens": out["prompt_tokens"],
                          "completion_tokens": out["completion_tokens"],
                          "total_tokens": out["prompt_tokens"]
                          + out["completion_tokens"]},
            }
        # /v1/completions
        prompt = body.get("prompt", "")
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        ids = self._request_ids(eng, body, prompt)
        fut = self._warm_start_future(eng, ids, tenant=tenant)
        if stream:
            sid = eng.start_stream(
                prompt_ids=ids,
                max_tokens=max_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, prefix_future=fut)
            self._note_stream(sid, eng, ids)
            return {"__sse_stream__": {"stream_id": sid,
                                       "model": self.model_id,
                                       "mode": "completion"}}
        out = eng.generate(prompt_ids=ids,
                           max_tokens=max_tokens,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, prefix_future=fut)
        self._publish_prefix(eng, ids)
        finish = ("length" if out["completion_tokens"] >= max_tokens
                  else "stop")
        return {
            "id": f"cmpl-{int(time.time() * 1e3)}",
            "object": "text_completion", "model": self.model_id,
            "choices": [{"index": 0, "text": out["text"],
                         "finish_reason": finish}],
            "usage": {"prompt_tokens": out["prompt_tokens"],
                      "completion_tokens": out["completion_tokens"],
                      "total_tokens": out["prompt_tokens"]
                      + out["completion_tokens"]},
        }


def build_openai_app(preset: str = "gpt2-tiny", max_batch: int = 4,
                     max_seq_len: int = 128, num_replicas: int = 1,
                     model_id: str = "ray-tpu-llm",
                     model_overrides: Optional[dict] = None,
                     num_tpu_chips: int = 0,
                     checkpoint: Optional[str] = None,
                     slo_config: Optional[dict] = None,
                     **engine_kwargs):
    """Deployment graph for an OpenAI-compatible server (reference
    `ray.serve.llm.build_openai_app`); run with
    `serve.run(app, route_prefix="/v1")`."""
    from ray_tpu.serve.api import deployment

    actor_options = {"num_cpus": 1}
    if num_tpu_chips:
        actor_options["num_tpu_chips"] = num_tpu_chips
    dep = deployment(OpenAIServer, name=f"openai-{model_id}",
                     num_replicas=num_replicas,
                     ray_actor_options=actor_options,
                     max_ongoing_requests=max_batch * 2,
                     slo_config=slo_config)
    return dep.bind(model_id=model_id, preset=preset, max_batch=max_batch,
                    max_seq_len=max_seq_len, model_overrides=model_overrides,
                    checkpoint=checkpoint, **engine_kwargs)


def build_llm_deployment(preset: str = "gpt2-tiny", max_batch: int = 4,
                         max_seq_len: int = 128, num_replicas: int = 1,
                         name: str = "llm",
                         model_overrides: Optional[dict] = None,
                         num_tpu_chips: int = 0,
                         checkpoint: Optional[str] = None,
                         slo_config: Optional[dict] = None,
                         **engine_kwargs):
    """Deployment for an LLM server (reference build_openai_app analog)."""
    from ray_tpu.serve.api import deployment

    actor_options = {"num_cpus": 1}
    if num_tpu_chips:
        actor_options["num_tpu_chips"] = num_tpu_chips
    dep = deployment(
        LLMServer, name=name, num_replicas=num_replicas,
        ray_actor_options=actor_options,
        max_ongoing_requests=max_batch * 2,
        slo_config=slo_config)
    return dep.bind(preset=preset, max_batch=max_batch,
                    max_seq_len=max_seq_len, model_overrides=model_overrides,
                    checkpoint=checkpoint, **engine_kwargs)
