"""Serve public API: @deployment, run, get handle, shutdown.

Parity with `python/ray/serve/api.py` (`serve.run` :665, `@serve.deployment`)
and `deployment.py`. The controller is a named actor
("serve-controller"), found or created on demand like the reference's
detached ServeController.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.serve.autoscaling import AutoscalingConfig
from ray_tpu.serve.controller import ServeController
from ray_tpu.serve.handle import DeploymentHandle

CONTROLLER_NAME = "serve-controller"


@dataclasses.dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    ray_actor_options: Optional[Dict[str, Any]] = None
    max_ongoing_requests: int = 8
    user_config: Any = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    init_args: tuple = ()
    init_kwargs: Optional[dict] = None
    visible_chips: Optional[list] = None
    # admission policy (serve/live_signals.SLOConfig or dict): the proxies
    # shed (429 / RESOURCE_EXHAUSTED + Retry-After) when the route's
    # EWMA-projected wait exceeds slo_s or every replica queue is at
    # max_queue
    slo_config: Optional[Any] = None
    # compiled=True: the proxies serve this deployment over a standing
    # CompiledServeChain (ring channels, lanes spread across replicas,
    # zero control-plane RPCs per warm request) with the dynamic handle
    # kept as the cold-start/failover path. chain_config tunes the chain
    # (lanes, batch_max, coalesce_ms, max_inflight, channel_capacity).
    compiled: bool = False
    chain_config: Optional[Dict[str, Any]] = None

    def bind(self, *args, **kwargs) -> "Deployment":
        return dataclasses.replace(self, init_args=args, init_kwargs=kwargs)

    def options(self, **overrides) -> "Deployment":
        return dataclasses.replace(self, **overrides)

    def to_config(self) -> dict:
        num = self.num_replicas
        auto = self.autoscaling_config
        if isinstance(auto, dict):
            auto = AutoscalingConfig(**auto)
        from ray_tpu.serve.live_signals import as_slo

        slo = as_slo(self.slo_config)
        return {
            "callable": self.func_or_class,
            "num_replicas": num,
            "ray_actor_options": self.ray_actor_options,
            "max_ongoing_requests": self.max_ongoing_requests,
            "user_config": self.user_config,
            "autoscaling_config": auto,
            "init_args": self.init_args,
            "init_kwargs": self.init_kwargs,
            "visible_chips": self.visible_chips,
            "slo_config": slo.to_dict() if slo is not None else None,
            "compiled": bool(self.compiled),
            "chain_config": self.chain_config,
        }


def deployment(_func_or_class: Optional[Callable] = None, *,
               name: Optional[str] = None, num_replicas: int = 1,
               ray_actor_options: Optional[dict] = None,
               max_ongoing_requests: int = 8,
               user_config: Any = None,
               autoscaling_config: Optional[Any] = None,
               slo_config: Optional[Any] = None,
               compiled: bool = False,
               chain_config: Optional[dict] = None):
    def deco(obj):
        return Deployment(
            func_or_class=obj,
            name=name or getattr(obj, "__name__", "deployment"),
            num_replicas=num_replicas,
            ray_actor_options=ray_actor_options,
            max_ongoing_requests=max_ongoing_requests,
            user_config=user_config,
            autoscaling_config=autoscaling_config,
            slo_config=slo_config,
            compiled=compiled,
            chain_config=chain_config)

    if _func_or_class is not None:
        return deco(_func_or_class)
    return deco


def _get_or_create_controller():
    from ray_tpu.core.api import _auto_init, get_actor

    _auto_init()
    try:
        return get_actor(CONTROLLER_NAME)
    except ValueError:
        return ServeController.options(
            name=CONTROLLER_NAME, get_if_exists=True, max_concurrency=16,
            num_cpus=0).remote()


def start(http_host: str = "127.0.0.1", http_port: int = 0) -> int:
    """Start the HTTP ingress proxy; returns the bound port (reference
    serve.start(http_options=...))."""
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.ensure_proxy.remote(http_host, http_port),
                       timeout=120)


def _resolve_composition(value, controller):
    """Deployment composition (reference deployment graphs /
    `serve.run(app)` with bound sub-deployments): a Deployment passed as
    an init arg deploys FIRST and arrives at the replica as a
    DeploymentHandle."""
    if isinstance(value, Deployment):
        run(value, _blocking=False)
        return DeploymentHandle(value.name, controller)
    if isinstance(value, (list, tuple)):
        return type(value)(_resolve_composition(v, controller)
                           for v in value)
    if isinstance(value, dict):
        return {k: _resolve_composition(v, controller)
                for k, v in value.items()}
    return value


def run(target: Deployment, *, name: Optional[str] = None,
        route_prefix: Optional[str] = None,
        compiled: Optional[bool] = None,
        _blocking: bool = True,
        _local_testing_mode: bool = False):
    """Deploy and return a handle (reference serve.run).

    `compiled=True` marks the deployment for the proxies' compiled
    ingress path (standing ring channels instead of per-request actor
    calls; see serve/compiled_chain.py) — equivalent to
    `@serve.deployment(compiled=True)`, overriding the decorator.

    `_local_testing_mode=True` runs the deployment IN-PROCESS with no
    cluster (reference local_testing_mode): unit-test deployment logic
    without actors/proxies."""
    if compiled is not None:
        target = dataclasses.replace(target, compiled=bool(compiled))
    if _local_testing_mode:
        return LocalDeploymentHandle(
            target if name is None else dataclasses.replace(target,
                                                            name=name))
    controller = _get_or_create_controller()
    # unconditional: _resolve_composition recurses through lists/dicts, so
    # a Deployment nested in e.g. init_args=([dep_a, dep_b],) deploys too
    # (a top-level-only trigger would ship it as a raw dataclass); it's an
    # identity transform when nothing matches
    target = dataclasses.replace(
        target,
        init_args=_resolve_composition(target.init_args, controller),
        init_kwargs=(_resolve_composition(target.init_kwargs, controller)
                     if target.init_kwargs else target.init_kwargs))
    dep_name = name or target.name
    ray_tpu.get(controller.deploy.remote(dep_name, target.to_config()),
                timeout=60)
    if route_prefix is not None:
        ray_tpu.get(controller.set_route.remote(route_prefix, dep_name),
                    timeout=30)
    handle = DeploymentHandle(dep_name, controller)
    if _blocking:
        _wait_healthy(controller, dep_name)
    return handle


def _wait_healthy(controller, dep_name: str, timeout: float = 60):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status = ray_tpu.get(controller.list_deployments.remote(), timeout=30)
        d = status.get(dep_name)
        if d and d["running"] >= min(d["target"], 1):
            return
        time.sleep(0.1)
    raise TimeoutError(f"deployment {dep_name} did not become ready")


def get_deployment_handle(deployment_name: str) -> DeploymentHandle:
    return DeploymentHandle(deployment_name, _get_or_create_controller())


def status() -> dict:
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.list_deployments.remote(), timeout=30)


def delete(deployment_name: str) -> None:
    controller = _get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(deployment_name),
                timeout=60)


def shutdown() -> None:
    from ray_tpu.core.api import get_actor

    try:
        grpc_proxy = get_actor("serve-grpc-proxy")
    except Exception:
        grpc_proxy = None
    if grpc_proxy is not None:
        try:
            ray_tpu.get(grpc_proxy.stop.remote(), timeout=10)
        except Exception:
            pass
        finally:
            # a detached proxy surviving here would hand later start_grpc()
            # callers a server wired to a dead controller
            try:
                ray_tpu.kill(grpc_proxy)
            except Exception:
                pass
    try:
        controller = get_actor(CONTROLLER_NAME)
    except (ValueError, RuntimeError):
        return
    try:
        ray_tpu.get(controller.shutdown_serve.remote(), timeout=30)
        ray_tpu.kill(controller)
    except Exception:
        pass


# ------------------------------------------------------ local testing mode
class _LocalResponse:
    """Synchronous stand-in for DeploymentResponse (.result())."""

    def __init__(self, value=None, exc=None):
        self._value, self._exc = value, exc

    def result(self, timeout: Optional[float] = None):
        if self._exc is not None:
            raise self._exc
        return self._value


class _LocalMethod:
    def __init__(self, inst, name: str):
        self._inst, self._name = inst, name

    def remote(self, *args, **kwargs) -> _LocalResponse:
        try:
            return _LocalResponse(getattr(self._inst, self._name)(
                *args, **kwargs))
        except Exception as e:  # surfaced at .result(), like the real path
            return _LocalResponse(exc=e)


class LocalDeploymentHandle:
    """In-process deployment execution — no cluster, no actors
    (reference `serve/_private/local_testing_mode.py`): the user callable
    is constructed HERE and every .remote() runs synchronously. For unit
    tests of deployment logic."""

    def __init__(self, dep: Deployment):
        c = dep.func_or_class
        if isinstance(c, type):
            self._inst = c(*dep.init_args, **(dep.init_kwargs or {}))
        else:
            self._inst = c
        if dep.user_config is not None and hasattr(self._inst,
                                                   "reconfigure"):
            self._inst.reconfigure(dep.user_config)
        self.deployment_name = dep.name

    def remote(self, *args, **kwargs) -> _LocalResponse:
        try:
            return _LocalResponse(self._inst(*args, **kwargs))
        except Exception as e:
            return _LocalResponse(exc=e)

    def options(self, method_name: Optional[str] = None,
                **_ignored) -> Any:
        if method_name:
            return _LocalMethod(self._inst, method_name)
        return self

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _LocalMethod(self._inst, name)
