"""Disaggregated prefill/decode serving over the P2P object data plane.

Reference: serve.llm's prefill/decode disaggregation behind KV-transfer
connectors (nixl/lmcache). Here the serve controller places TWO replica
sets — a prefill pool and a decode pool — as separate deployments with
distinct resource labels (`ray_actor_options["resources"]`), so the
two-level scheduler lands them on separately-provisioned nodes. The KV
path is the PR 7 object data plane, NOT the head:

  decode replica --(actor call)--> prefill replica
      prefill runs the prompt pass, `kv_cache.export_prefix` serializes
      the pooled blocks, `ray_tpu.put` seals the blob into the prefill
      node's store; the ObjectRef travels back in the reply.
  decode replica --(P2P pull)--> prefill node
      the decode side waits for the gossiped object directory to learn
      the blob's location (bounded), then `ray_tpu.get` pulls it through
      its node's PullManager — one network crossing, zero head RPCs on
      the warm path — and `kv_cache.import_prefix` installs the blocks,
      so decode skips prefill for the covered span.

Every step degrades gracefully: a dead prefill pool, a lost blob, or a
mismatched architecture just means the decode engine runs the prefill
locally (correctness never depends on the transfer).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.llm import LLMServer


class _RpcAudit:
    """Head-RPC audit hooks for acceptance drills and ops debugging:
    records this process's head-connection traffic between start/stop
    (the zero-head-RPCs-on-the-warm-path contract is interposer-verified
    from inside the replica, where the KV shipping actually happens)."""

    def __init__(self):
        self._events: List[tuple] = []
        self._hook = None

    def start(self) -> bool:
        from ray_tpu.core import protocol

        if self._hook is not None:
            return False
        events = self._events = []

        def hook(conn_name, kind, method):
            if conn_name == "head":
                events.append((kind, method))

        protocol.add_rpc_interposer(hook)
        self._hook = hook
        return True

    def stop(self) -> List[tuple]:
        from ray_tpu.core import protocol

        if self._hook is not None:
            protocol.remove_rpc_interposer(self._hook)
            self._hook = None
        events, self._events = self._events, []
        return events


class PrefillServer:
    """Prefill-pool replica: runs prompt passes and exports KV blobs into
    the object store for decode replicas to pull. Every export is also
    PUBLISHED into the cluster prefix store (content hash -> blob binding
    on the gossiped directory), so later requests for the same prefix —
    on ANY decode replica — warm-start without even calling this pool."""

    def __init__(self, cluster_prefix_cache: bool = True, **engine_kwargs):
        from ray_tpu.serve.llm import LLMEngine

        engine_kwargs.setdefault("enable_prefix_caching", True)
        self.engine = LLMEngine(**engine_kwargs)
        self.prefix_store = None
        if cluster_prefix_cache and self.engine.kv is not None:
            from ray_tpu.serve import prefix_store as _ps

            self.prefix_store = _ps.store_for_engine(self.engine)
        self._lock = threading.Lock()
        self.prefills = 0
        self.blobs_exported = 0
        self.tokens_exported = 0
        self._audit = _RpcAudit()

    def prefill(self, prompt_ids: List[int]) -> Dict[str, Any]:
        """Run (or reuse) the prompt's prefill and ship its KV blocks to
        the decode caller. Production-sized blobs seal into the object
        store (the ref rides back in the reply; the bytes stay on this
        node until the decode replica pulls them P2P through the data
        plane). Blobs under the store's inline threshold ride the direct
        actor reply itself — inline objects never enter the gossiped
        directory, so a store round trip for them would route through
        the head for nothing."""
        with self._lock:
            self.prefills += 1
        blob = self.engine.export_prefix(prompt_ids=list(prompt_ids))
        if blob is None or not blob.get("ids"):
            return {"ref": None, "n_tokens": 0}
        with self._lock:
            self.blobs_exported += 1
            self.tokens_exported += len(blob["ids"])
        out = {"n_tokens": len(blob["ids"]), "block_size": blob["block_size"]}
        from ray_tpu.core.store import INLINE_THRESHOLD

        if blob["k"].nbytes + blob["v"].nbytes <= INLINE_THRESHOLD:
            return {**out, "blob": blob}
        ref = ray_tpu.put(blob)
        if self.prefix_store is not None:
            # publish-on-prefill: bind the content hash to THIS blob so
            # the whole cluster shares the export (the store pins its own
            # ref; pin-level dedup keeps re-prefills from re-announcing)
            try:
                self.prefix_store.publish(blob, ref=ref)
            except Exception:
                pass
        return {**out, "ref": ref}

    def live_signal_extra(self) -> dict:
        """Resident-prefix routing hint merged into this replica's
        gossiped load row: decode handles route PREFILL calls to the
        pool replica advertising the longest matching resident prefix."""
        if self.engine.kv is None:
            return {}
        return {"prefix_roots":
                [h.hex() for h in self.engine.kv.recent_chain_hashes()]}

    def stats(self) -> dict:
        out = self.engine.engine_stats()
        with self._lock:
            out.update({"role": "prefill", "prefills": self.prefills,
                        "blobs_exported": self.blobs_exported,
                        "tokens_exported": self.tokens_exported})
        if self.engine.kv is not None:
            out["kv_cache"] = self.engine.kv.stats()
        if self.prefix_store is not None:
            out["prefix_store"] = self.prefix_store.stats()
        return out

    def rpc_audit_start(self) -> bool:
        return self._audit.start()

    def rpc_audit_stop(self) -> List[tuple]:
        return self._audit.stop()

    def check_health(self):
        if not self.engine._thread.is_alive():
            raise RuntimeError("prefill engine loop died")


class DisaggLLMServer(LLMServer):
    """Decode-pool replica: completions API surface; prompts whose KV
    isn't resident fall through the residency tiers — local engine pool,
    cluster prefix store (any replica's export, via the gossiped
    directory + P2P pull, zero head RPCs warm), prefill pool RPC — and
    the blob import overlaps decode of other lanes instead of blocking
    the request thread."""

    def __init__(self, prefill_handle=None, directory_wait_s: float = 2.0,
                 prefill_timeout_s: float = 120.0,
                 cluster_prefix_cache: bool = True, **engine_kwargs):
        engine_kwargs.setdefault("enable_prefix_caching", True)
        super().__init__(cluster_prefix_cache=cluster_prefix_cache,
                         **engine_kwargs)
        # arrives as a live DeploymentHandle via deployment composition
        self.prefill_handle = prefill_handle
        self.directory_wait_s = directory_wait_s
        self.prefill_timeout_s = prefill_timeout_s
        self._lock = threading.Lock()
        self.prefill_fetches = 0
        self.plane_fetches = 0      # blobs pulled via the object data plane
        self.store_fetches = 0      # blobs resolved from the cluster store
        self.local_prefix_hits = 0
        self.fetch_errors = 0
        self._audit = _RpcAudit()

    # ------------------------------------------------------- KV fetching
    def _wait_directory(self, ref) -> bool:
        """Bounded wait for the gossiped object directory to resolve the
        blob to a serving node: the announcement rides the cluster_view
        broadcast, so a beat of patience buys a head-free P2P pull
        (timeout falls back to the cold-miss path inside get())."""
        try:
            client = ray_tpu.core.api._global_client()
        except Exception:
            return False
        deadline = time.monotonic() + self.directory_wait_s
        while time.monotonic() < deadline:
            try:
                if ref.id in client.local_metas:
                    return True     # same-node blob: already local
                locs = client.object_dir.locations(ref.id)
                if locs and any(client.cluster_view.data_addr_of(h)
                                for h in locs):
                    return True
            except Exception:
                return False
            time.sleep(0.01)
        return False

    def _prefix_future(self, ids: List[int]):
        """Async prefill fetch: kick the residency-tier fall-through onto
        the prefetch executor and hand the engine a blob future — the
        request thread enqueues immediately and OTHER lanes keep decoding
        while this prompt's KV crosses the network (the engine imports on
        its own thread at admission). None when the local pool already
        covers the prompt (a full block of gain is the bar — below that
        the fetch costs more than the prefill it saves)."""
        if self.engine.kv is None or len(ids) < 2:
            return None
        if self.prefill_handle is None and self.prefix_store is None:
            return None
        kv = self.engine.kv
        covered = kv.peek_prefix_len(ids[:-1])
        if (len(ids) - 1) - covered < kv.block_size:
            with self._lock:
                self.local_prefix_hits += 1
            return None
        return self._prefix_submit(self._fetch_prefix_blob, list(ids),
                                   covered)

    def _fetch_prefix_blob(self, ids: List[int],
                          covered: int) -> Optional[dict]:
        """Executor thread: cluster store first (directory lookup from
        cache + P2P pull — zero head RPCs on the warm path, no prefill
        RPC at all), then the prefill pool with a prefix-affinity routing
        hint. None on total failure: decode-local prefill is always
        correct."""
        kv = self.engine.kv
        need = ids[:-1]
        store = self.prefix_store
        if store is not None:
            hit = store.lookup(need)
            if hit is not None and hit["n"] > covered:
                # a store hit only replaces the prefill RPC when it
                # covers most of the uncovered prompt: a shallow hit on a
                # long prompt would leave the decode replica prefilling
                # the long tail locally — exactly what disaggregation
                # exists to avoid — so those fall through to the pool
                # (affinity-routed to the replica holding the prefix)
                remaining_after = len(need) - hit["n"]
                deep_enough = remaining_after <= max(
                    kv.block_size, (len(need) - covered) // 2)
                if deep_enough or self.prefill_handle is None:
                    blob = store.fetch(hit)
                    if blob is not None:
                        with self._lock:
                            self.store_fetches += 1
                        return blob
                    # owner died / blob gone mid-fetch: fall through to
                    # the prefill pool (which re-exports and re-announces)
        if self.prefill_handle is None:
            return None
        try:
            from ray_tpu.serve.kv_cache import chain_hashes

            h = self.prefill_handle.options(
                method_name="prefill",
                prefix_hint=[ph.hex() for ph, _n in
                             chain_hashes(need, kv.block_size)])
            res = h.remote(list(ids)).result(timeout=self.prefill_timeout_s)
            blob = res.get("blob")
            via_plane = blob is None
            if via_plane:
                ref = res.get("ref")
                if ref is None:
                    return None
                self._wait_directory(ref)
                blob = ray_tpu.get(ref, timeout=self.prefill_timeout_s)
            with self._lock:
                self.prefill_fetches += 1
                self.plane_fetches += 1 if via_plane else 0
            # the blob ref is dropped here, not free()d: free is a head
            # round trip, while a dropped borrow GCs through the refcount
            # plane's batched pushes — the warm path stays head-RPC-free
            return blob
        except Exception:
            # degraded mode: decode-side prefill (correct, just slower)
            with self._lock:
                self.fetch_errors += 1
            return None

    def prefix_store_probe(self, prompt_ids: List[int]) -> Optional[int]:
        """Debug/drill surface: covered-token count the cluster store
        would warm-start this prompt with right now (cached directory
        only — no fetch, and uncounted so polls don't skew the hit/miss
        counters)."""
        if self.prefix_store is None:
            return None
        hit = self.prefix_store.lookup(list(prompt_ids), count=False)
        return None if hit is None else hit["n"]

    # ---------------------------------------------------------- requests
    def __call__(self, request: Any) -> dict:
        body = request if isinstance(request, dict) else getattr(
            request, "json", None) or {}
        ids = self._request_ids(self.engine, body)
        out = self.engine.generate(
            prompt_ids=ids,
            max_tokens=int(body.get("max_tokens", 16)),
            temperature=float(body.get("temperature", 0.0)),
            prefix_future=self._prefix_future(ids),
            prefix_wait_s=self.prefill_timeout_s,
            # the fetch wait parks INSIDE generate now (the old sync
            # _ensure_prefix ran before it): the deadline must cover the
            # full fetch window PLUS the decode budget
            timeout=self.prefill_timeout_s + 120.0)
        # Degraded-mode decode-local prefills (prefill pool down, store
        # miss) are prefix-cache material like any other: publish them so
        # the NEXT replica to see this prefix warm-starts from the store
        # instead of re-prefilling. Content-addressed dedup in
        # maybe_publish makes the warm-path case (prefix was imported,
        # nothing newly computed) a no-op.
        self._publish_prefix(self.engine, ids)
        return {
            "object": "text_completion",
            "choices": [{"text": out["text"], "index": 0,
                         "token_ids": out["token_ids"],
                         "finish_reason": "length"}],
            "usage": {"prompt_tokens": out["prompt_tokens"],
                      "completion_tokens": len(out["token_ids"])},
        }

    def stats(self) -> dict:
        out = super().stats()
        kv = self.engine.kv
        with self._lock:
            out.update({"role": "decode",
                        "prefill_fetches": self.prefill_fetches,
                        "plane_fetches": self.plane_fetches,
                        "store_fetches": self.store_fetches,
                        "blocks_imported":
                            self.engine.prefix_blocks_imported,
                        "tokens_imported":
                            self.engine.prefix_blocks_imported
                            * (kv.block_size if kv is not None else 0),
                        "local_prefix_hits": self.local_prefix_hits,
                        "fetch_errors": self.fetch_errors})
        return out

    def rpc_audit_start(self) -> bool:
        return self._audit.start()

    def rpc_audit_stop(self) -> List[tuple]:
        return self._audit.stop()


def build_disagg_llm_deployment(
        preset: str = "gpt2-tiny", max_seq_len: int = 128,
        name: str = "llm-disagg",
        prefill_replicas: int = 1, decode_replicas: int = 1,
        prefill_resources: Optional[dict] = None,
        decode_resources: Optional[dict] = None,
        prefill_max_batch: int = 2, decode_max_batch: int = 4,
        model_overrides: Optional[dict] = None,
        checkpoint: Optional[str] = None, seed: int = 0,
        kv_blocks: int = 64, kv_block_size: int = 16,
        num_tpu_chips: int = 0,
        cluster_prefix_cache: bool = True,
        autoscaling_config=None, slo_config=None,
        **engine_kwargs):
    """Two-pool deployment graph: `{name}-prefill` and `{name}` (decode,
    the routable front). Distinct `*_resources` labels steer each pool's
    replicas through the two-level scheduler (e.g. prefill on
    compute-heavy nodes, decode on HBM-heavy nodes). Run with
    `serve.run(app, route_prefix=...)`; the returned handle fronts the
    decode pool."""
    from ray_tpu.serve.api import deployment

    shared = dict(preset=preset, max_seq_len=max_seq_len, seed=seed,
                  model_overrides=model_overrides, checkpoint=checkpoint,
                  kv_blocks=kv_blocks, kv_block_size=kv_block_size,
                  cluster_prefix_cache=cluster_prefix_cache,
                  **engine_kwargs)
    pre_opts: Dict[str, Any] = {"num_cpus": 1}
    dec_opts: Dict[str, Any] = {"num_cpus": 1}
    if num_tpu_chips:
        pre_opts["num_tpu_chips"] = num_tpu_chips
        dec_opts["num_tpu_chips"] = num_tpu_chips
    if prefill_resources:
        pre_opts["resources"] = dict(prefill_resources)
    if decode_resources:
        dec_opts["resources"] = dict(decode_resources)
    prefill = deployment(
        PrefillServer, name=f"{name}-prefill",
        num_replicas=prefill_replicas, ray_actor_options=pre_opts,
        max_ongoing_requests=prefill_max_batch * 2,
    ).bind(max_batch=prefill_max_batch, **shared)
    decode = deployment(
        DisaggLLMServer, name=name, num_replicas=decode_replicas,
        ray_actor_options=dec_opts,
        max_ongoing_requests=decode_max_batch * 2,
        autoscaling_config=autoscaling_config, slo_config=slo_config,
    ).bind(prefill_handle=prefill, max_batch=decode_max_batch, **shared)
    return decode
