"""ray_tpu.serve: model serving — controller, replicas, routing, batching,
autoscaling. Reference: `python/ray/serve/` (SURVEY §2.5)."""

from ray_tpu.serve.api import (Deployment, delete, deployment,
                               get_deployment_handle, run, shutdown, status)
from ray_tpu.serve.autoscaling import AutoscalingConfig
from ray_tpu.serve.batching import batch
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse

__all__ = [
    "Deployment", "deployment", "run", "delete", "shutdown", "status",
    "get_deployment_handle", "AutoscalingConfig", "batch",
    "DeploymentHandle", "DeploymentResponse",
]
