"""ray_tpu.serve: model serving — controller, replicas, HTTP proxy, routing,
batching, multiplexing, autoscaling, LLM deployments.
Reference: `python/ray/serve/` (SURVEY §2.5)."""

from ray_tpu.serve.api import (Deployment, delete, deployment,
                               get_deployment_handle, run, shutdown, start,
                               status)
from ray_tpu.serve.autoscaling import AutoscalingConfig
from ray_tpu.serve.batching import batch
from ray_tpu.serve.compiled_chain import ChainResponse, CompiledServeChain
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.grpc_proxy import start_grpc
from ray_tpu.serve.live_signals import SLOConfig
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "Deployment", "deployment", "run", "delete", "shutdown", "start",
    "start_grpc",
    "status", "get_deployment_handle", "AutoscalingConfig", "SLOConfig",
    "batch",
    "DeploymentHandle", "DeploymentResponse", "multiplexed",
    "get_multiplexed_model_id",
    "CompiledServeChain", "ChainResponse",
]
