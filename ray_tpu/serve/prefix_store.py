"""Cluster-wide content-addressed KV/prefix cache tier.

Serving millions of users who share prompts means the expensive part of
TTFT — prefilling a shared system/few-shot prefix — should be computed
once per CLUSTER, not once per request. PR 10's disaggregation shipped
KV point-to-point per request (`serve/disagg.py`); this module makes
paged-KV prefix blobs first-class citizens of the PR 7 object data
plane instead:

- **publish**: a replica that just prefilled a prompt exports its pooled
  blocks (`kv_cache.export_prefix`), seals the blob into its node's shm
  store (`ray_tpu.put`), pins the ref in a bounded LRU so the bytes stay
  alive, and announces `content hash -> blob object id` to the head with
  one fire-and-forget push. The binding rides the next cluster_view
  broadcast as a directory prefix row (`core/object_directory.py`).
- **lookup**: ANY replica resolves "who already computed this prefix"
  from its process-cached directory — longest matching chain hash first,
  residency-checked — with ZERO RPCs. Same-process publications
  short-circuit through the pin table without waiting for gossip.
- **fetch**: the blob pulls through the node PullManager like any other
  object (one network crossing per node, LRU replica cache, multi-source
  failover) — zero head RPCs on the warm path.

Residency tiers a request falls through, cheapest first: replica-local
engine cache (`PagedKVCache.peek_prefix_len`) -> this process's pinned
publications -> any cluster replica via directory + P2P pull -> prefill
pool RPC -> decode-local prefill. Every tier degrades to the next on
any failure; correctness never depends on a cache hit.

Multi-tenant: the store key is the BASE model's weight identity, so LoRA
adapters over one base share prefix entries (one blob per prefix
cluster-wide); hit/miss counters are tagged per tenant so per-adapter
cache efficiency stays observable.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional

from ray_tpu.serve.kv_cache import chain_hashes

# ------------------------------------------------------------------ metrics
_metrics = None


def _get_metrics():
    global _metrics
    if _metrics is None:
        from ray_tpu.util import metrics as m

        _metrics = {
            "hits": m.Counter(
                "prefix_store_hits_total",
                "Cluster prefix-store lookups that resolved a resident "
                "prefix blob", tag_keys=("tenant",)),
            "misses": m.Counter(
                "prefix_store_misses_total",
                "Cluster prefix-store lookups with no resident binding",
                tag_keys=("tenant",)),
            "bytes": m.Counter(
                "prefix_store_bytes_total",
                "KV bytes fetched from the cluster prefix store",
                tag_keys=("tenant",)),
            "inline_skipped": m.Counter(
                "prefix_store_inline_skipped_total",
                "Prefix blobs NOT published because they serialized "
                "below the object store's inline threshold "
                "(core/store.py INLINE_THRESHOLD, 100 KiB): inline "
                "objects ride actor replies instead of the sealed-object "
                "plane, so a directory binding could never serve a P2P "
                "pull. Small models / short prefixes land here — a "
                "nonzero count is WHY lookups miss, not a bug"),
        }
    return _metrics


def model_cache_key(weights_id: str, n_layer: int, n_head: int,
                    head_dim: int, dtype, block_size: int) -> str:
    """KV-compatibility key: two engines share prefix entries iff their
    keys match (same weights, same cache geometry). LoRA engines pass the
    BASE model's weights_id so adapters share base-model prefixes."""
    return (f"{weights_id}|L{n_layer}H{n_head}D{head_dim}"
            f"|{dtype}|bs{block_size}")


def _client():
    """The process's ray client, or None outside an initialized runtime
    (standalone engines in unit tests): every store operation silently
    no-ops without a cluster."""
    try:
        from ray_tpu.core import api as core_api

        if not core_api.is_initialized():
            return None
        return core_api._global_client()
    except Exception:
        return None


def store_for_engine(engine, max_pins: int = 64,
                     fetch_timeout_s: float = 30.0
                     ) -> Optional["PrefixStoreClient"]:
    """Store client keyed by an LLMEngine's weight identity + cache
    geometry; None when the engine has no prefix cache to share."""
    key = engine.prefix_model_key
    if key is None:
        return None
    return PrefixStoreClient(key, engine.kv.block_size, max_pins=max_pins,
                             fetch_timeout_s=fetch_timeout_s)


class PrefixStoreClient:
    """One process's facade over the cluster prefix tier (thread-safe:
    replica request threads and prefetch executors share it)."""

    def __init__(self, model_key: str, block_size: int,
                 max_pins: int = 64, fetch_timeout_s: float = 30.0):
        self.model_key = model_key
        self.block_size = block_size
        self.max_pins = max_pins
        self.fetch_timeout_s = fetch_timeout_s
        # tip hash -> (ref, [(boundary hash, n_tokens), ...]): one pinned
        # blob serves EVERY block boundary it covers — a prompt sharing
        # only the system prefix of a published prompt still matches at
        # the shared depth
        self._pins: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._pin_rows: Dict[bytes, tuple] = {}   # boundary -> (tip, n)
        self._lock = threading.Lock()
        # lifetime counters (stats()/tests; the tagged Counters feed
        # /metrics): per-tenant hit/miss/fetch accounting
        self.hits = 0
        self.misses = 0
        self.fetches = 0
        self.fetch_errors = 0
        self.bytes_fetched = 0
        self.published = 0
        self.inline_skipped = 0
        self.reannounced = 0
        self.hits_by_tenant: Dict[str, int] = {}
        # head-restart resilience (the pool_reconcile pattern): the head
        # rebuilds prefix bindings from publisher truth — on reconnect
        # this client re-pushes announce rows for every live pin, so
        # bindings survive a head restart instead of waiting for the
        # next fresh export per prefix. Registered here AND retried at
        # publish time (a store built before ray_tpu.init would
        # otherwise never arm the hook).
        self._reconnect_cb = None
        self._ensure_reconnect_hook(_client())

    def _ensure_reconnect_hook(self, client) -> None:
        """Idempotently arm the reconnect re-announce hook once a core
        client exists. WeakMethod: the client must not keep an evicted
        store alive; a fired hook whose store died self-unregisters."""
        if client is None or self._reconnect_cb is not None:
            return
        import weakref

        ref = weakref.WeakMethod(self.reannounce_pins)

        def _on_reconnect(_ref=ref, _client=client):
            m = _ref()
            if m is None:
                # store was GC'd: self-unregister so a long-lived
                # process recreating engines doesn't accumulate dead
                # closures on the shared client
                try:
                    _client.remove_reconnect_callback(_on_reconnect)
                except Exception:
                    pass
                return
            m()

        try:
            client.add_reconnect_callback(_on_reconnect)
            self._reconnect_cb = _on_reconnect
        except Exception:
            self._reconnect_cb = None

    # ------------------------------------------------------------- publish
    def _bound_in_directory(self, phash: bytes, client) -> bool:
        """Is this boundary already bound to a RESIDENT blob (announced by
        any replica, adopted from the broadcast)?"""
        try:
            from ray_tpu.core.ids import ObjectID

            ent = (client.object_dir.prefixes.get(self.model_key)
                   or {}).get(phash)
            return (ent is not None
                    and ObjectID(ent["oid"]) in client.object_dir.entries)
        except Exception:
            return False

    def publish(self, blob: Optional[dict], ref=None) -> bool:
        """Seal an exported KV blob into the object store (or reuse a
        caller-provided `ref` of the already-sealed blob), pin it, and
        announce a content-address row at EVERY block boundary it covers
        — a later prompt that shares only the first j blocks still
        resolves this blob at depth j. Boundaries the cluster already has
        a resident binding for are skipped (shared prefixes are stored
        and announced once cluster-wide). Returns True when new bindings
        were announced. Sub-inline blobs are skipped: inline objects
        never enter the gossiped directory, so a binding for one could
        never serve a P2P warm start."""
        if not blob or not blob.get("ids"):
            return False
        client = _client()
        if client is None:
            return False
        self._ensure_reconnect_hook(client)
        chain = chain_hashes(list(blob["ids"]), self.block_size)
        if not chain:
            return False
        tip, _tip_n = chain[-1]
        with self._lock:
            if tip in self._pins:
                return False     # already published by this process
        rows = [(ph, n) for ph, n in chain
                if ph not in self._pin_rows
                and not self._bound_in_directory(ph, client)]
        if not rows:
            return False         # every boundary already served
        import ray_tpu

        try:
            if ref is None:
                ref = ray_tpu.put(blob)
            meta = client.local_metas.get(ref.id)
            from ray_tpu.core.object_directory import PULLABLE_KINDS

            if meta is None or meta.kind not in PULLABLE_KINDS:
                # inline (< core/store.py INLINE_THRESHOLD = 100 KiB
                # serialized): rides actor replies, not the plane. Count
                # it — silently dropping these made small-model tests
                # chase phantom directory misses.
                with self._lock:
                    self.inline_skipped += 1
                try:
                    _get_metrics()["inline_skipped"].inc()
                except Exception:
                    pass
                return False
            client.head_push(
                "announce_prefix", model_key=self.model_key,
                oid=ref.id.binary(), block_size=self.block_size,
                rows=rows)
        except Exception:
            return False
        evicted: Dict[bytes, list] = {}
        with self._lock:
            self._pins[tip] = (ref, rows)
            for ph, n in rows:
                self._pin_rows[ph] = (tip, n)
            self.published += 1
            while len(self._pins) > self.max_pins:
                _old_tip, (_old_ref, old_rows) = \
                    self._pins.popitem(last=False)
                for ph, _n in old_rows:
                    # a boundary rebound by a newer pin stays announced
                    if self._pin_rows.get(ph, (None,))[0] == _old_tip:
                        self._pin_rows.pop(ph, None)
                        evicted.setdefault(
                            _old_ref.id.binary(), []).append(ph)
        for old_oid, phashes in evicted.items():
            # dropping the ref releases the bytes through the refcount
            # plane; the explicit withdraw retires the bindings promptly
            # instead of leaving consumers to discover the free record.
            # oid-scoped: the head keeps a binding another replica has
            # since rebound to its own live blob
            try:
                client.head_push("withdraw_prefix",
                                 model_key=self.model_key, phashes=phashes,
                                 oid=old_oid)
            except Exception:
                pass
        return True

    def maybe_publish(self, kv, ids: List[int], exporter=None) -> bool:
        """Export + publish the prompt's pooled blocks unless the cluster
        already holds a resident binding for the full chain — shared
        prefixes are stored ONCE cluster-wide, so the dedup check runs
        before paying the device->host export copy. `exporter` overrides
        the raw pool export; engine callers pass `LLMEngine.export_pooled`
        so the copy runs on the engine thread (the pool is unlocked
        engine-owned state — a racing export could bind another request's
        bytes under this prompt's content hash)."""
        chain = chain_hashes(list(ids), self.block_size)
        if not chain:
            return False
        tip = chain[-1][0]
        with self._lock:
            if tip in self._pin_rows:
                return False
        client = _client()
        if client is not None and self._bound_in_directory(tip, client):
            return False           # another replica already owns it
        from ray_tpu.serve.kv_cache import export_prefix

        if exporter is None:
            exporter = lambda i: export_prefix(kv, i)  # noqa: E731
        return self.publish(exporter(list(ids)))

    def reannounce_pins(self) -> int:
        """Re-push announce rows for every pinned publication (fired by
        the client's reconnect hook). The restarted head lost its prefix
        index; its objects come back through pool_reconcile, and these
        pushes rebind their content hashes — same source-of-truth
        inversion, zero new RPC channels. Idempotent head-side (a
        binding that already exists is overwritten with itself)."""
        client = _client()
        if client is None:
            return 0
        with self._lock:
            pins = [(ref, list(rows)) for ref, rows in self._pins.values()]
        n = 0
        for ref, rows in pins:
            try:
                client.head_push(
                    "announce_prefix", model_key=self.model_key,
                    oid=ref.id.binary(), block_size=self.block_size,
                    rows=rows)
                n += 1
            except Exception:
                pass
        with self._lock:
            self.reannounced += n
        return n

    # -------------------------------------------------------------- lookup
    def lookup(self, ids: List[int], tenant: str = "base",
               count: bool = True) -> Optional[dict]:
        """Longest resident prefix binding covering `ids`, zero RPCs:
        this process's pins first (no gossip round trip for same-process
        publications), then the broadcast-fed directory — whichever
        covers more tokens wins. Returns {"ph", "oid", "n", "bs"}.
        `count=False` keeps probes/polls out of the miss counters so they
        keep measuring request-path cache efficiency. HITS are counted on
        a successful `fetch` — a binding the caller never uses (too
        shallow for the disagg policy, or its fetch fails) is not cache
        efficiency."""
        chain = chain_hashes(list(ids), self.block_size)
        if not chain:
            return None
        best: Optional[dict] = None
        with self._lock:
            for phash, n_tokens in reversed(chain):
                owner = self._pin_rows.get(phash)
                if owner is None:
                    continue
                pinned = self._pins.get(owner[0])
                if pinned is not None:
                    best = {"ph": phash, "oid": pinned[0].binary(),
                            "n": owner[1], "bs": self.block_size}
                    break
        client = _client()
        if client is not None:
            try:
                hit = client.object_dir.longest_prefix(self.model_key,
                                                       chain)
            except Exception:
                hit = None
            if hit is not None and (best is None or hit["n"] > best["n"]):
                best = hit
        if count and best is None:
            with self._lock:
                self.misses += 1
            _get_metrics()["misses"].inc(tags={"tenant": tenant})
        return best

    # --------------------------------------------------------------- fetch
    def fetch(self, hit: dict, tenant: str = "base") -> Optional[dict]:
        """Pull a binding's blob over the object data plane (node
        PullManager: in-flight dedup, replica failover, LRU cache). None
        on any failure — the caller degrades to the next residency tier."""
        client = _client()
        if client is None:
            return None
        import ray_tpu
        from ray_tpu.core.ids import ObjectID
        from ray_tpu.core.object_ref import ObjectRef

        try:
            blob = ray_tpu.get(ObjectRef(ObjectID(hit["oid"])),
                               timeout=self.fetch_timeout_s)
        except Exception:
            with self._lock:
                self.fetch_errors += 1
            return None
        if not isinstance(blob, dict) or "k" not in blob:
            with self._lock:
                self.fetch_errors += 1
            return None
        size = int(blob["k"].nbytes + blob["v"].nbytes)
        with self._lock:
            self.fetches += 1
            self.bytes_fetched += size
            # a HIT is a blob the tier actually delivered: lookups whose
            # binding goes unused (shallow, or fetch fails) don't count
            self.hits += 1
            self.hits_by_tenant[tenant] = \
                self.hits_by_tenant.get(tenant, 0) + 1
        m = _get_metrics()
        m["hits"].inc(tags={"tenant": tenant})
        m["bytes"].inc(size, tags={"tenant": tenant})
        return blob

    # --------------------------------------------------------------- stats
    def pinned_hashes(self) -> List[bytes]:
        """Every boundary hash this process's pinned blobs can serve."""
        with self._lock:
            return list(self._pin_rows)

    def stats(self) -> dict:
        with self._lock:
            return {"model_key": self.model_key,
                    "block_size": self.block_size,
                    "pinned": len(self._pins),
                    "published": self.published,
                    "inline_skipped": self.inline_skipped,
                    "reannounced": self.reannounced,
                    "store_hits": self.hits,
                    "store_misses": self.misses,
                    "store_fetches": self.fetches,
                    "store_fetch_errors": self.fetch_errors,
                    "store_bytes_fetched": self.bytes_fetched,
                    "hits_by_tenant": dict(self.hits_by_tenant)}
