"""DeploymentHandle + client-side router (power-of-two-choices).

Parity with `python/ray/serve/handle.py` (DeploymentHandle/DeploymentResponse)
and `_private/router.py:368` + `request_router/pow_2_router.py`: the handle
tracks per-replica in-flight counts locally, samples two replicas and picks
the shorter queue. The queue each choice compares is the LIVE one — the
gossiped replica load rows (queue depth / EWMA latency from
`state.list_serve_stats()`, cached ~1s in serve/live_signals.py) blended
with the local in-flight counts, so a handle sees load other routers and
proxies put on a replica, not just its own.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.core.api import _global_client

ROUTING_TABLE_REFRESH_S = 1.0

import contextlib as _contextlib

_NULL_CM = _contextlib.nullcontext()


class DeploymentResponse:
    """Future-like wrapper over the result ObjectRef."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout: Optional[float] = None):
        return ray_tpu.get(self._ref, timeout=timeout)

    @property
    def ref(self):
        return self._ref


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 method_name: str = "__call__",
                 multiplexed_model_id: Optional[str] = None,
                 prefix_hint: Optional[list] = None):
        self.deployment_name = deployment_name
        self._controller = controller
        self._method_name = method_name
        self._model_id = multiplexed_model_id
        # prefix-affinity routing: the prompt's chain hashes (hex, prefix
        # order) — the pick prefers the replica whose gossiped row
        # advertises the deepest resident match (disagg decode->prefill)
        self._prefix_hint = list(prefix_hint) if prefix_hint else None
        self._table: Dict[str, Any] = {}
        self._models: Dict[str, list] = {}
        self._table_version = -1
        self._table_ts = 0.0
        self._inflight: Dict[str, int] = {}
        self._lock = threading.Lock()

    def __reduce__(self):
        # handles travel into replicas as init args (deployment
        # composition); reconstruct against the receiving process's
        # controller — locks/tables are process-local state
        return (_rebuild_handle, (self.deployment_name, self._method_name,
                                  self._model_id))

    # --------------------------------------------------------------- remote
    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                prefix_hint: Optional[list] = None) -> "DeploymentHandle":
        h = DeploymentHandle(self.deployment_name, self._controller,
                             method_name or self._method_name,
                             multiplexed_model_id or self._model_id,
                             prefix_hint or self._prefix_hint)
        h._table, h._table_version = self._table, self._table_version
        h._table_ts, h._inflight = self._table_ts, self._inflight
        h._models = self._models
        h._lock = self._lock
        return h

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._submit(self._method_name, args, kwargs)

    def _submit(self, method: str, args, kwargs) -> DeploymentResponse:
        from ray_tpu.util import tracing

        replica_tag, handle = self._pick_replica()
        if self._model_id:
            kwargs = {**kwargs, "_multiplexed_model_id": self._model_id}
        with self._lock:
            self._inflight[replica_tag] = self._inflight.get(replica_tag, 0) + 1
        # submission span (only when the caller traces): the replica-side
        # execute span parents to it, so handle routing decisions are
        # visible inside the request's trace
        span_cm = (tracing.start_span(
            f"serve.handle.{self.deployment_name}",
            attributes={"ray_tpu.op": "serve_handle",
                        "replica": replica_tag, "method": method})
            if tracing.is_recording() else _NULL_CM)
        with span_cm:
            ref = handle.handle_request.remote(method, args, kwargs)

        def _done():
            with self._lock:
                self._inflight[replica_tag] = max(
                    0, self._inflight.get(replica_tag, 1) - 1)

        _global_client().add_done_callback(ref, _done)
        self._maybe_push_metrics()
        return DeploymentResponse(ref)

    # --------------------------------------------------------------- router
    def _refresh_table(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._table_ts < ROUTING_TABLE_REFRESH_S:
            return
        table = ray_tpu.get(self._controller.get_routing_table.remote(
            self.deployment_name), timeout=30)
        if table is None:
            raise KeyError(f"deployment {self.deployment_name!r} not found")
        with self._lock:
            self._table = table["replicas"]
            self._models = table.get("models", {})
            self._table_version = table["version"]
            self._table_ts = now
            self._inflight = {t: self._inflight.get(t, 0) for t in self._table}

    def _pick_replica(self):
        self._refresh_table()
        deadline = time.monotonic() + 30
        while not self._table:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for deployment {self.deployment_name!r}")
            time.sleep(0.1)
            self._refresh_table(force=True)
        from ray_tpu.serve import live_signals

        # TTL-cached head fetch OUTSIDE the lock (it can be a round trip)
        live = live_signals.get_cache()
        try:
            live.refresh()
        except Exception:
            pass
        now = time.time()
        max_age = live_signals._flag("serve_live_signal_max_age_s", 5.0)

        with self._lock:
            tags = list(self._table)
            if self._model_id:
                # prefer replicas that already have the model loaded
                warm = [t for t in tags
                        if self._model_id in self._models.get(t, [])]
                if warm:
                    tags = warm

            def score_of(t):
                return live_signals.replica_score(
                    self._inflight.get(t, 0),
                    live.row(self.deployment_name, t), now, max_age)

            if self._prefix_hint:
                # prefix-affinity first: the replica advertising the
                # deepest resident match skips recomputing the prefix.
                # Only CURRENT route-table tags are candidates, so the
                # stale row of a departed replica can't draw traffic.
                tag = live_signals.pick_prefix_affinity(
                    tags, self._prefix_hint,
                    lambda t: live.row(self.deployment_name, t),
                    score_of, now, max_age)
                if tag is not None:
                    return tag, self._table[tag]
            # power of two choices on LIVE queue depth (gossiped rows
            # blended with local in-flight; EWMA latency breaks ties)
            tag = live_signals.pick_pow2(
                tags, score_of,
                lambda t: live_signals.ewma_of(
                    live.row(self.deployment_name, t)))
            return tag, self._table[tag]

    def _maybe_push_metrics(self):
        with self._lock:
            total = sum(self._inflight.values())
        try:
            self._controller.record_handle_metrics.remote(
                self.deployment_name, total)
        except Exception:
            pass


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._submit(self._method, args, kwargs)


def _rebuild_handle(deployment_name: str, method_name: str,
                    model_id):
    from ray_tpu import serve as _serve

    h = _serve.get_deployment_handle(deployment_name)
    if method_name != "__call__" or model_id:
        h = h.options(method_name=method_name,
                      multiplexed_model_id=model_id)
    return h
