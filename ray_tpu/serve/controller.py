"""ServeController actor: owns app/deployment state, reconciles replicas.

Parity with `python/ray/serve/_private/controller.py:91` +
`deployment_state.py` (replica state machine: start/stop/health/rolling
update) + `autoscaling_state.py` (metrics-driven scaling), collapsed into one
reconcile loop. Routers learn replica sets by versioned polling (the
long-poll host role, `_private/long_poll.py`).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.autoscaling import (AutoscalingConfig,
                                       calculate_desired_num_replicas,
                                       desired_from_live_load)
from ray_tpu.serve.replica import ReplicaActor

RECONCILE_INTERVAL_S = 0.25
HEALTH_CHECK_INTERVAL_S = 2.0
# a replica that has never answered a health check gets this long before
# an unresponsive probe is treated as death: model-serving replicas spend
# tens of seconds in __init__ (engine build + XLA compile) with actor
# calls queued behind it, and killing them mid-compile just restarts the
# compile forever. A provably-dead actor (ActorDiedError) is replaced
# immediately regardless.
REPLICA_INIT_GRACE_S = 120.0


class DeploymentInfo:
    def __init__(self, name: str, config: dict):
        self.name = name
        self.config = config
        self.replicas: Dict[str, Any] = {}      # tag -> handle
        self.replica_meta: Dict[str, dict] = {} # tag -> {healthy, ongoing}
        self.version = 0
        self.target_replicas = config.get("num_replicas", 1)
        self.autoscaling: Optional[AutoscalingConfig] = None
        if config.get("autoscaling_config"):
            ac = config["autoscaling_config"]
            self.autoscaling = (ac if isinstance(ac, AutoscalingConfig)
                                else AutoscalingConfig(**ac))
            self.target_replicas = self.autoscaling.min_replicas
        self._counter = 0

    def next_tag(self) -> str:
        self._counter += 1
        return f"{self.name}#{self._counter}"


@ray_tpu.remote
class ServeController:
    def __init__(self):
        self.deployments: Dict[str, DeploymentInfo] = {}
        self.routes: Dict[str, str] = {}        # route prefix -> deployment
        self.multiplexed: Dict[str, Dict[str, list]] = {}  # dep -> tag -> ids
        self._proxy = None
        self._proxy_port: Optional[int] = None
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._last_health = 0.0
        self._thread = threading.Thread(target=self._reconcile_loop,
                                        daemon=True, name="serve-reconcile")
        self._thread.start()

    # ----------------------------------------------------------------- API
    def deploy(self, name: str, config: dict):
        """Create or update (rolling) a deployment."""
        with self._lock:
            existing = self.deployments.get(name)
            if existing is not None:
                old_replicas = dict(existing.replicas)
                info = DeploymentInfo(name, config)
                info.version = existing.version + 1
                self.deployments[name] = info
                # rolling update: stop old replicas; reconcile starts new ones
                for tag, h in old_replicas.items():
                    self._stop_replica(h)
            else:
                self.deployments[name] = DeploymentInfo(name, config)
        self._reconcile_once()
        return True

    def delete_deployment(self, name: str):
        with self._lock:
            info = self.deployments.pop(name, None)
        if info:
            for h in info.replicas.values():
                self._stop_replica(h)
        return True

    def get_routing_table(self, name: str):
        with self._lock:
            info = self.deployments.get(name)
            if info is None:
                return None
            slo = info.config.get("slo_config")
            if slo is not None and not isinstance(slo, dict):
                slo = slo.to_dict()
            return {"version": info.version,
                    "replicas": {tag: h for tag, h in info.replicas.items()},
                    "models": dict(self.multiplexed.get(name, {})),
                    "slo": slo,
                    # compiled ingress: the proxies stand up a
                    # CompiledServeChain for this deployment and route
                    # warm requests over its rings (serve/compiled_chain)
                    "compiled": bool(info.config.get("compiled")),
                    "chain": info.config.get("chain_config"),
                    # lets the proxy tell a DEGRADED chain (lanes
                    # compiled over fewer replicas than intended, e.g.
                    # mid-replacement) from a settled one and poll fast
                    # until the lanes re-spread
                    "target_replicas": info.target_replicas}

    # ------------------------------------------------------- routes / proxy
    def set_route(self, route_prefix: str, deployment_name: str):
        with self._lock:
            self.routes[route_prefix] = deployment_name
        return True

    def get_routes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self.routes)

    def record_multiplexed_models(self, deployment: str, tag: str, ids: list):
        with self._lock:
            self.multiplexed.setdefault(deployment, {})[tag] = list(ids)
        return True

    def ensure_proxy(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Start (or return) the HTTP ingress proxy; returns its port."""
        with self._lock:
            if self._proxy_port is not None:
                return self._proxy_port
        from ray_tpu.serve.proxy import ProxyActor

        # handle to ourselves, resolvable from any process
        self_handle = ray_tpu.get_actor("serve-controller")
        proxy = ProxyActor.options(
            name="serve-proxy", get_if_exists=True, max_concurrency=64,
            num_cpus=0).remote(self_handle)
        proxy_port = ray_tpu.get(proxy.start.remote(host, port), timeout=60)
        with self._lock:
            self._proxy = proxy
            self._proxy_port = proxy_port
        return proxy_port

    def list_deployments(self):
        with self._lock:
            return {name: {"target": d.target_replicas,
                           "running": len(d.replicas),
                           "version": d.version}
                    for name, d in self.deployments.items()}

    def record_handle_metrics(self, name: str, ongoing: int):
        """Routers push their in-flight counts (autoscaling input)."""
        with self._lock:
            info = self.deployments.get(name)
            if info is not None:
                info.config.setdefault("_handle_metrics", {})["driver"] = (
                    ongoing, time.time())
        return True

    def shutdown_serve(self):
        self._stop.set()
        with self._lock:
            deployments = list(self.deployments.values())
            self.deployments = {}
            self.routes = {}
            proxy, self._proxy, self._proxy_port = self._proxy, None, None
        if proxy is not None:
            try:
                ray_tpu.get(proxy.stop.remote(), timeout=10)
                ray_tpu.kill(proxy)
            except Exception:
                pass
        for info in deployments:
            for h in info.replicas.values():
                self._stop_replica(h)
        return True

    # ------------------------------------------------------------ reconcile
    def _reconcile_loop(self):
        while not self._stop.wait(RECONCILE_INTERVAL_S):
            try:
                self._reconcile_once()
            except Exception:
                traceback.print_exc()

    def _reconcile_once(self):
        with self._lock:
            infos = list(self.deployments.values())
        for info in infos:
            self._autoscale(info)
            self._scale_to_target(info)
        if time.monotonic() - self._last_health > HEALTH_CHECK_INTERVAL_S:
            self._last_health = time.monotonic()
            for info in infos:
                self._health_check(info)

    def _scale_to_target(self, info: DeploymentInfo):
        with self._lock:
            current = len(info.replicas)
            delta = info.target_replicas - current
            if delta > 0:
                for _ in range(delta):
                    self._start_replica(info)
            elif delta < 0:
                for tag in list(info.replicas)[:(-delta)]:
                    h = info.replicas.pop(tag)
                    info.replica_meta.pop(tag, None)
                    info.version += 1
                    self._stop_replica(h)

    def _start_replica(self, info: DeploymentInfo):
        cfg = info.config
        tag = info.next_tag()
        opts = dict(cfg.get("ray_actor_options") or {})
        opts.setdefault("num_cpus", 0)
        opts["max_concurrency"] = cfg.get("max_ongoing_requests", 8)
        handle = ReplicaActor.options(**opts).remote(
            info.name, tag, cfg["callable"], cfg.get("init_args"),
            cfg.get("init_kwargs"), cfg.get("user_config"),
            visible_chips=cfg.get("visible_chips"))
        info.replicas[tag] = handle
        info.replica_meta[tag] = {"healthy": True, "started": time.time()}
        info.version += 1

    def _stop_replica(self, handle):
        def _drain_and_kill():
            try:
                ray_tpu.get(handle.prepare_for_shutdown.remote(), timeout=10)
            except Exception:
                pass
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass

        threading.Thread(target=_drain_and_kill, daemon=True).start()

    def _health_check(self, info: DeploymentInfo):
        from ray_tpu.core.exceptions import ActorDiedError

        dead = []
        with self._lock:
            replicas = dict(info.replicas)
        for tag, h in replicas.items():
            try:
                status = ray_tpu.get(h.check_health.remote(), timeout=10)
                if not status["healthy"]:
                    dead.append(tag)
                else:
                    with self._lock:
                        info.replica_meta[tag] = {**info.replica_meta.get(tag, {}),
                                                  "ongoing": status["ongoing"],
                                                  "ready": True}
            except Exception as e:
                with self._lock:
                    meta = info.replica_meta.get(tag, {})
                if (not meta.get("ready")
                        and not isinstance(e, ActorDiedError)
                        and time.time() - meta.get("started", 0)
                        < REPLICA_INIT_GRACE_S):
                    # probe timed out but the replica is still in its init
                    # window (probes queue behind a long __init__): give it
                    # the grace period before declaring death
                    continue
                dead.append(tag)
        if dead:
            with self._lock:
                for tag in dead:
                    h = info.replicas.pop(tag, None)
                    info.replica_meta.pop(tag, None)
                    info.version += 1
                    if h is not None:
                        try:
                            ray_tpu.kill(h)
                        except Exception:
                            pass
            # reconcile will start replacements (reference deployment_state
            # replica-died path)

    def _autoscale(self, info: DeploymentInfo):
        if info.autoscaling is None:
            return
        # primary signal: the gossiped live-load rows (queue depth + EWMA
        # latency via state.list_serve_stats) — scale-up reacts at gossip
        # latency instead of the health-check poll cadence. Controller-
        # polled counts stay as the fallback when the signal plane is
        # cold/stale (fresh deployment, head restart, idle).
        desired = None
        rows = self._live_serve_rows().get(info.name, {})
        if rows:
            with self._lock:
                live = [r for tag, r in rows.items() if tag in info.replicas]
                current = max(len(info.replicas), 1)
            desired = desired_from_live_load(info.autoscaling, live, current)
        if desired is not None:
            with self._lock:
                info.target_replicas = desired
            return
        with self._lock:
            ongoing = sum(m.get("ongoing", 0)
                          for m in info.replica_meta.values())
            hm = info.config.get("_handle_metrics", {})
            for _, (count, ts) in hm.items():
                if time.time() - ts < 5.0:
                    ongoing = max(ongoing, count)
            desired = calculate_desired_num_replicas(
                info.autoscaling, ongoing, max(len(info.replicas), 1))
            info.target_replicas = desired

    def _live_serve_rows(self) -> dict:
        """{deployment: {tag: load_row}} from the shared live-signal
        cache; {} when the telemetry plane is unreachable."""
        try:
            from ray_tpu.serve import live_signals

            cache = live_signals.get_cache()
            cache.refresh()
            return cache.snapshot()
        except Exception:
            return {}
