"""Replica actor: wraps the user's deployment callable.

Parity with `python/ray/serve/_private/replica.py`: runs user __init__ once,
serves requests with an ongoing-request gauge, health checks, reconfigure
with user_config, graceful drain. TPU twist: a replica scheduled with
`num_tpu_chips=k` pins itself to k chips via TPU_VISIBLE_CHIPS before any
jax import, so multiple replicas subdivide a host (reference
`tpu.py:283-323` set_current_process_visible_accelerator_ids).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Optional

import ray_tpu


@ray_tpu.remote
class ReplicaActor:
    def __init__(self, deployment_name: str, replica_tag: str,
                 cls_or_fn, init_args, init_kwargs, user_config,
                 visible_chips: Optional[list] = None):
        if visible_chips:
            from ray_tpu.core.resources import set_visible_chips

            set_visible_chips(visible_chips)
        self.deployment_name = deployment_name
        self.replica_tag = replica_tag
        self._ongoing = 0
        self._ongoing_lock = threading.Lock()
        self._total = 0
        self._executing = 0
        self._latency_samples = 0
        self._ewma_latency_s = 0.0
        self._healthy = True
        self._draining = False
        self._metrics = None
        if isinstance(cls_or_fn, type):
            self.callable = cls_or_fn(*(init_args or ()), **(init_kwargs or {}))
        else:
            self.callable = cls_or_fn
        if user_config is not None:
            self._apply_user_config(user_config)

    def _apply_user_config(self, user_config):
        reconfigure = getattr(self.callable, "reconfigure", None)
        if reconfigure is not None:
            reconfigure(user_config)

    # ------------------------------------------------------------- requests
    def handle_request(self, method: str, args: tuple, kwargs: dict):
        if self._draining:
            raise RuntimeError(f"replica {self.replica_tag} is draining")
        model_id = kwargs.pop("_multiplexed_model_id", None)
        with self._ongoing_lock:
            self._ongoing += 1
            self._total += 1
        # publish on ADMIT as well as completion: live-signal routing and
        # admission control read the gossiped queue depth, which must
        # rise while a burst is still executing, not after it drains
        self._publish_load(self._ewma_latency_s)
        t0 = time.perf_counter()
        try:
            from ray_tpu.serve import multiplex
            from ray_tpu.util import tracing

            if model_id is not None:
                multiplex._set_request_model_id(model_id)
            multiplex._replica_reporter.set(self._report_models)
            target = (self.callable if method == "__call__"
                      and not isinstance(self.callable, type)
                      and callable(self.callable)
                      else None)
            if target is None or method != "__call__":
                target = getattr(self.callable, method)
            # child of the actor-call execute span (which carried the
            # proxy's root context across the process boundary)
            with tracing.start_span(
                    "serve.replica",
                    attributes={"ray_tpu.op": "serve_replica",
                                "deployment": self.deployment_name,
                                "replica": self.replica_tag,
                                "method": method}):
                with self._ongoing_lock:
                    self._executing += 1
                try:
                    return target(*args, **kwargs)
                finally:
                    with self._ongoing_lock:
                        self._executing -= 1
        finally:
            dur = time.perf_counter() - t0
            with self._ongoing_lock:
                self._ongoing -= 1
                # EWMA over the last ~10 requests: the live-load signal
                # routers and the head watchdog read. Seeded on the first
                # completed SAMPLE (a cold burst of N concurrent firsts
                # must not seed at ~dur/N via an admissions count)
                self._latency_samples += 1
                self._ewma_latency_s = (
                    dur if self._latency_samples == 1
                    else 0.9 * self._ewma_latency_s + 0.1 * dur)
            self._publish_load(dur)

    def _publish_load(self, last_latency_s: float) -> None:
        """Queue depth / in-flight / EWMA latency, published two ways on
        the SAME existing telemetry channel (the per-process metrics
        push — zero new RPCs): gauges for `/metrics` and a workload row
        the head merges into `state.list_serve_stats()` and
        `GET /api/workloads`."""
        try:
            from ray_tpu.util import metrics as m

            if self._metrics is None:
                tags = ("deployment", "replica")
                self._metrics = {
                    "queue": m.Gauge(
                        "serve_replica_queue_depth",
                        "Requests admitted to the replica and not yet "
                        "finished (executing + waiting)", tag_keys=tags),
                    "inflight": m.Gauge(
                        "serve_replica_inflight",
                        "Requests currently inside user code on the "
                        "replica", tag_keys=tags),
                }
            tags = {"deployment": self.deployment_name,
                    "replica": self.replica_tag}
            self._metrics["queue"].set(self._ongoing, tags=tags)
            self._metrics["inflight"].set(self._executing, tags=tags)
            row = {
                "deployment": self.deployment_name,
                "queue_depth": self._ongoing,
                "inflight": self._executing,
                "ewma_latency_s": round(self._ewma_latency_s, 6),
                "last_latency_s": round(last_latency_s, 6),
                "total": self._total,
            }
            # deployment-specific routing hints (e.g. a prefill replica's
            # resident-prefix hashes) ride the same gossiped row: zero
            # new channels, and routers see them exactly as fresh as the
            # load signal itself
            extra = getattr(self.callable, "live_signal_extra", None)
            if extra is not None:
                try:
                    row.update(extra() or {})
                except Exception:
                    pass
            m.publish_workload("serve_replica", self.replica_tag, row)
        except Exception:
            pass

    # -------------------------------------------------------- compiled chain
    def handle_chain(self, batch: list) -> list:
        """Compiled-chain entry (serve/compiled_chain.py): one ring entry
        carries a LIST of request values. Per-item failures come back as
        error markers — one bad request must not fail its batch
        neighbours, and an infra failure (draining replica) marks every
        item failover-eligible instead of raising out of the exec loop
        (which would wedge the chain until the driver's read times out).
        A callable exposing `batch_call` (LLMEngine servers) gets the
        whole entry at once so continuous batching applies across it."""
        from ray_tpu.serve.compiled_chain import (CHAIN_ERR, infra_error,
                                                  unwrap_traced)

        if self._draining:
            return [infra_error(f"replica {self.replica_tag} is draining")
                    for _ in batch]
        # sampled requests arrive in their trace envelope: peel the W3C
        # carrier per item so the callable only ever sees plain values;
        # outputs re-wrap below with THIS stage's span context so the
        # next stage (and the final chain.deliver) parent into the same
        # trace — the compiled path's submit→stage→stage chain
        carriers = []
        peeled = []
        for v in batch:
            c, inner = unwrap_traced(v)
            carriers.append(c)
            peeled.append(inner)
        batch = peeled
        n = len(batch)
        with self._ongoing_lock:
            self._ongoing += n
            self._total += n
            self._executing += n
        t0 = time.perf_counter()
        try:
            # error markers from an UPSTREAM stage pass through untouched
            # — feeding one into this stage's callable would either
            # swallow an infra failure or re-wrap it as a user error,
            # breaking the failover contract on multi-stage chains
            from ray_tpu.serve.compiled_chain import is_chain_error

            live = [(i, v) for i, v in enumerate(batch)
                    if not is_chain_error(v)]
            out = list(batch)
            bc = getattr(self.callable, "batch_call", None)
            if bc is not None:
                try:
                    results = bc([v for _i, v in live])
                    if not isinstance(results, list) \
                            or len(results) != len(live):
                        # a short/odd return must not silently leave
                        # request values in the output positions (they
                        # would be delivered to callers as results)
                        raise RuntimeError(
                            f"batch_call returned "
                            f"{len(results) if isinstance(results, list) else type(results)} "
                            f"for {len(live)} inputs")
                except Exception:
                    results = [infra_error(traceback.format_exc())
                               for _ in live]
                for (i, _v), r in zip(live, results):
                    out[i] = r
            else:
                for i, v in live:
                    try:
                        # __init__ already resolved self.callable to an
                        # instance or function; a non-callable raises
                        # into the per-item error marker
                        out[i] = self.callable(v)
                    except Exception as e:  # user error: this item only
                        out[i] = {CHAIN_ERR: repr(e), "infra": False}
            if any(c is not None for c in carriers):
                try:
                    from ray_tpu.serve.compiled_chain import TracedValue
                    from ray_tpu.util import tracing

                    stage_dur = time.perf_counter() - t0
                    wall_end = time.time()
                    for i, c in enumerate(carriers):
                        # error markers pass through UNwrapped: the chain
                        # client's failover check must see them directly
                        if c is None or is_chain_error(out[i]):
                            continue
                        with tracing.start_span(
                                f"chain.stage.{self.deployment_name}",
                                carrier=c,
                                attributes={"ray_tpu.op": "chain_stage",
                                            "replica": self.replica_tag,
                                            "batch": n}) as sp:
                            if sp is not None:
                                # backdate to cover the whole stage exec
                                sp.start_ts = wall_end - stage_dur
                                out[i] = TracedValue(
                                    {"traceparent": sp.traceparent()},
                                    out[i])
                except Exception:
                    pass
            return out
        finally:
            dur = time.perf_counter() - t0
            with self._ongoing_lock:
                self._ongoing -= n
                self._executing -= n
                self._latency_samples += 1
                per = dur / max(1, n)
                self._ewma_latency_s = (
                    per if self._latency_samples == 1
                    else 0.9 * self._ewma_latency_s + 0.1 * per)
            # rate-limited: the compiled hot path must not turn load
            # publishing into per-entry overhead; the gossiped row stays
            # fresh at the metrics-push cadence
            now = time.monotonic()
            if now - getattr(self, "_chain_pub_ts", 0.0) > 1.0:
                self._chain_pub_ts = now
                self._publish_load(dur)

    def _report_models(self, model_ids):
        """Push the loaded-model set so routers prefer warm replicas."""
        try:
            ctrl = ray_tpu.get_actor("serve-controller")
            ctrl.record_multiplexed_models.remote(
                self.deployment_name, self.replica_tag, list(model_ids))
        except Exception:
            pass

    def loaded_model_ids(self):
        from ray_tpu.serve.multiplex import loaded_model_ids_of

        return loaded_model_ids_of(self.callable)

    # -------------------------------------------------------------- control
    def reconfigure(self, user_config):
        self._apply_user_config(user_config)
        return True

    def check_health(self):
        user_check = getattr(self.callable, "check_health", None)
        if user_check is not None:
            try:
                user_check()
            except Exception:
                self._healthy = False
                return {"healthy": False, "detail": traceback.format_exc()}
        return {"healthy": True, "ongoing": self._ongoing,
                "total": self._total}

    def queue_len(self):
        return self._ongoing

    def prepare_for_shutdown(self, drain_timeout_s: float = 5.0):
        self._draining = True
        deadline = time.monotonic() + drain_timeout_s
        while self._ongoing > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        shutdown = getattr(self.callable, "__del__", None)
        return self._ongoing == 0
