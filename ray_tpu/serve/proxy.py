"""HTTP ingress proxy actor (aiohttp) + async client-side router.

Parity with the reference's per-node proxy actors
(`python/ray/serve/_private/proxy.py`, starlette/uvicorn) re-based on
aiohttp: the proxy polls the controller for the route table (long-poll-lite,
`long_poll.py` role), matches the longest route prefix, routes to a
replica, and awaits the reply on the event loop — requests never block the
loop thread.

Serving-plane additions: the router's pow-2 choice compares LIVE load
(gossiped queue depth / EWMA latency from `state.list_serve_stats()`,
blended with local in-flight counts — see serve/live_signals.py) with
prompt-prefix affinity kept as the tiebreak; the proxy runs SLO-aware
admission control per route (429 + Retry-After when the projected wait
exceeds the route's SLO or every replica's queue is at its bound), and
failed submissions to a dying replica fail over to a healthy one instead
of surfacing a 500.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve import live_signals

ROUTE_REFRESH_S = 1.0
# routing-table refresh cadence while the deployment's ingress chain is
# LIVE: replica death is fenced by the chain's actor-death pubsub (no
# table poll needed to notice it), so the poll only exists to catch
# autoscale-up drift — stretched so a warm compiled request window makes
# ZERO control-plane RPCs from the proxy process (the ISSUE-19 contract,
# interposer-verified by tests/test_compiled_proxy.py)
COMPILED_ROUTE_REFRESH_S = 30.0
SUBMIT_ATTEMPTS = 3     # original try + failovers on replica death

# compiled ingress (serve.run(compiled=True)): instead of a per-request
# actor call, the proxy stands up ONE CompiledServeChain per compiled
# deployment and writes request batches into its input rings / reads the
# output rings — zero control-plane RPCs on the warm path, lanes spread
# across the deployment's replicas, and the chain's own fence machinery
# fails requests over to the dynamic handle path on replica death
# (external clients never see a 500 for infra reasons). Streaming (SSE)
# requests stay on the dynamic path: stream state is replica-affine and
# needs the submit_on(tag) follow-up calls.


async def _chain_result(resp, timeout: float):
    """Await a ChainResponse on the event loop WITHOUT parking an
    executor thread per in-flight request: the chain's drainer thread
    completes the response, and the done-callback trampolines the value
    back onto the loop."""
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    def _done(r):
        def _set():
            if fut.cancelled():
                return
            if r._exc is not None:
                fut.set_exception(r._exc)
            else:
                fut.set_result(r._value)
        loop.call_soon_threadsafe(_set)

    resp.add_done_callback(_done)
    return await asyncio.wait_for(fut, timeout)

# ------------------------------------------------------- serve telemetry
_serve_metrics = None


def _get_serve_metrics():
    """Lazy per-process serve metrics (proxy + gRPC ingress share them);
    they ride the ordinary metrics pusher to the head's /metrics."""
    global _serve_metrics
    if _serve_metrics is None:
        from ray_tpu.util import metrics as m

        _serve_metrics = {
            "request_seconds": m.Histogram(
                "serve_request_seconds",
                "Ingress request latency by matched route and status code",
                tag_keys=("route", "code")),
            "admitted": m.Counter(
                "serve_admitted_total",
                "Ingress requests admitted past the route's admission "
                "policy", tag_keys=("route",)),
            "shed": m.Counter(
                "serve_shed_total",
                "Ingress requests shed by SLO-aware admission control "
                "(HTTP 429 / gRPC RESOURCE_EXHAUSTED)",
                tag_keys=("route", "reason")),
            "failover": m.Counter(
                "serve_failover_total",
                "Requests re-routed to another replica after an "
                "infrastructure failure (replica death/drain)",
                tag_keys=("route",)),
        }
    return _serve_metrics


def note_admission(route: str, shed: Optional[dict]) -> Optional[int]:
    """Count one admission decision (shared by the HTTP and gRPC
    ingresses so the counters and the Retry-After formatting can't
    drift); for a shed, returns the Retry-After hint in whole seconds
    (ceiling, >= 1)."""
    try:
        m = _get_serve_metrics()
        if shed is not None:
            m["shed"].inc(tags={"route": route, "reason": shed["reason"]})
        else:
            m["admitted"].inc(tags={"route": route})
    except Exception:
        pass
    if shed is None:
        return None
    return max(1, int(-(-float(shed["retry_after_s"]) // 1)))


def _is_infra_error(e: BaseException) -> bool:
    """Failures that justify re-routing to ANOTHER replica: the replica
    died, drained, or its connection dropped. User exceptions raised
    inside the deployment are NOT retried — they would re-run user code
    for a deterministic failure.

    NOTE: this gives ingress requests at-least-once semantics under
    replica death — a handler that ran to completion just before its
    process died may run again elsewhere. That matches the actor layer's
    own lost-reply resend contract (client._fast_actor_send) and the
    usual serving tradeoff: handlers observable from outside should be
    idempotent per request."""
    from ray_tpu.core import protocol
    from ray_tpu.core.exceptions import (ActorDiedError,
                                         ActorUnavailableError,
                                         WorkerCrashedError)

    if isinstance(e, (ActorDiedError, ActorUnavailableError,
                      WorkerCrashedError, protocol.ConnectionLost,
                      ConnectionRefusedError)):
        return True
    if isinstance(e, RuntimeError):
        msg = str(e)
        return "draining" in msg or "is gone" in msg
    return False


class Request:
    """What a deployment callable receives for an HTTP request."""

    def __init__(self, method: str, path: str, query: Dict[str, str],
                 headers: Dict[str, str], body: bytes, json: Any):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body
        self.json = json

    def __getitem__(self, key):  # dict-style access to the json body
        return (self.json or {})[key]

    def get(self, key, default=None):
        return (self.json or {}).get(key, default)

    def __reduce__(self):
        return (Request, (self.method, self.path, self.query, self.headers,
                          self.body, self.json))


PREFIX_MAP_CAP = 2048       # remembered prompt-prefix -> replica pairs
PREFIX_IMBALANCE_SLACK = 4  # cache affinity yields when this much busier


def prompt_prefix_key(json_body) -> Optional[str]:
    """Stable key for the prompt prefix of an LLM-shaped request body
    (reference prefix_aware_router.py:39 — route requests sharing a
    prefix to the replica whose KV cache already holds it)."""
    if not isinstance(json_body, dict):
        return None
    text = None
    if isinstance(json_body.get("prompt"), str):
        text = json_body["prompt"]
    elif isinstance(json_body.get("messages"), list):
        try:
            text = "".join(str(m.get("content", ""))
                           for m in json_body["messages"])
        except AttributeError:
            return None
    if not text:
        return None
    import hashlib

    return hashlib.blake2b(text[:256].encode(), digest_size=8).hexdigest()


class _AsyncRouter:
    """Live-load replica choice (pow-2 on gossiped queue depth blended
    with local in-flight counts), all-async; prompt-prefix affinity as
    the tiebreak; per-route SLO admission; failover on replica death."""

    def __init__(self, controller, deployment: str):
        self._controller = controller
        self._deployment = deployment
        self._table: Dict[str, Any] = {}
        self._model_map: Dict[str, list] = {}
        self._slo: Optional[dict] = None
        self._ts = 0.0
        self._inflight: Dict[str, int] = {}
        # compiled ingress state (set from the routing table)
        self._compiled = False
        self._chain_config: Optional[dict] = None
        self._chain = None
        self._chain_starting = False
        self._target_replicas = 0
        # requests queued behind a scaled-to-zero deployment; pushed to
        # the controller as the wake-up demand signal
        self._cold_waiters = 0
        from collections import OrderedDict

        self._prefix_map: "OrderedDict[str, str]" = OrderedDict()

    async def _refresh(self, force: bool = False):
        now = time.monotonic()
        interval = ROUTE_REFRESH_S
        chain = self._chain
        if chain is not None and chain.is_compiled():
            # live chain: replica death fences via the actor-death
            # pubsub, so only autoscale drift needs the poll — stretch
            # it, UNLESS the chain is degraded (its lanes cover fewer
            # distinct replicas than min(lanes, target), e.g. it
            # recompiled over the survivor while the controller was
            # still replacing a dead replica): then poll fast until the
            # replacement lands and maybe_rebalance re-spreads the lanes
            lanes = chain.lane_targets()
            spread = {t for lane in lanes for _d, t in lane}
            want = min(len(lanes), self._target_replicas or 1)
            if len(spread) >= want:
                interval = COMPILED_ROUTE_REFRESH_S
        if not force and now - self._ts < interval:
            return
        ref = self._controller.get_routing_table.remote(self._deployment)
        table = await ref
        if table:
            self._table = table["replicas"]
            self._model_map = table.get("models", {})
            self._slo = table.get("slo")
            self._compiled = bool(table.get("compiled"))
            self._chain_config = table.get("chain")
            self._target_replicas = int(table.get("target_replicas") or 0)
            self._inflight = {t: self._inflight.get(t, 0)
                              for t in self._table}
            # a dead replica's stale prefix mapping would eat a failed
            # first route before the pow-2 fallback: evict entries whose
            # replica left the route table
            for key in [k for k, tag in self._prefix_map.items()
                        if tag not in self._table]:
                del self._prefix_map[key]
            if self._compiled:
                self._maybe_start_chain()
                chain = self._chain
                if chain is not None and chain.is_compiled():
                    # replica set drifted (autoscale-up has no death event
                    # to fence on): let the chain decide, rate-limited. In
                    # an executor — a rebalance fence drains in-flight
                    # entries, which must not block the event loop.
                    tags = set(self._table)
                    asyncio.get_running_loop().run_in_executor(
                        None, lambda: chain.maybe_rebalance(
                            {self._deployment: tags}))
        self._ts = now

    def _maybe_start_chain(self) -> None:
        """Stand up the deployment's ingress chain once, off the event
        loop (compile + warm-up are blocking control-plane work). Until
        it goes live — and again whenever it is fenced — requests flow
        through the dynamic path below, which IS the cold-start/failover
        contract."""
        if self._chain is not None or self._chain_starting:
            return
        self._chain_starting = True
        cfg = dict(self._chain_config or {})
        # default lane count: one per replica, floor 2, so every replica
        # gets a standing ring and a single replica still overlaps entries
        cfg.setdefault("lanes", max(2, len(self._table)))
        dep, controller = self._deployment, self._controller

        def _start():
            try:
                from ray_tpu.serve.compiled_chain import CompiledServeChain

                chain = CompiledServeChain(
                    [dep], controller=controller, plane="serve_proxy",
                    **cfg)
                chain.start()
                self._chain = chain
            except Exception:
                # retry on a later refresh (e.g. replicas still starting)
                self._chain_starting = False

        threading.Thread(target=_start, daemon=True,
                         name=f"proxy-chain-{dep}").start()

    def chain_status(self) -> dict:
        chain = self._chain
        if chain is None:
            return {"compiled": self._compiled, "chain": False}
        return {"compiled": self._compiled, "chain": True,
                "live": chain.is_compiled(),
                "generation": chain.generation,
                "lane_targets": chain.lane_targets(),
                "stats": dict(chain.stats)}

    def shutdown_chain(self) -> None:
        chain, self._chain = self._chain, None
        self._chain_starting = False
        if chain is not None:
            chain.shutdown()

    def _live_cache(self):
        # lazy: unit tests build routers via __new__ with hand-set state
        live = getattr(self, "_live", None)
        if live is None:
            live = self._live = live_signals.get_cache()
        return live

    def _drop_replica(self, tag: str) -> None:
        """Stop routing to a replica this process just watched fail; the
        next table refresh re-adds it only if the controller still
        believes in it."""
        self._table.pop(tag, None)
        for key in [k for k, t in self._prefix_map.items() if t == tag]:
            del self._prefix_map[key]

    def _score(self, tag: str, now: float, max_age_s: float) -> float:
        return live_signals.replica_score(
            self._inflight.get(tag, 0),
            self._live_cache().row(self._deployment, tag), now, max_age_s)

    def _choose(self, tags, prefix_key: Optional[str]) -> str:
        now = time.time()
        max_age = live_signals._flag("serve_live_signal_max_age_s", 5.0)
        if prefix_key is not None and len(tags) > 1:
            # cache affinity: a replica that served this prefix holds its
            # KV blocks — prefer it unless clearly busier than the rest
            # (reference PrefixAwareRequestRouter's imbalance threshold)
            mapped = self._prefix_map.get(prefix_key)
            if mapped in self._table and mapped in tags:
                floor = min(self._score(t, now, max_age) for t in tags)
                if (self._score(mapped, now, max_age)
                        <= floor + PREFIX_IMBALANCE_SLACK):
                    self._prefix_map.move_to_end(prefix_key)
                    return mapped
        live = self._live_cache()
        tag = live_signals.pick_pow2(
            tags,
            lambda t: self._score(t, now, max_age),
            lambda t: live_signals.ewma_of(live.row(self._deployment, t)))
        if prefix_key is not None:
            self._prefix_map[prefix_key] = tag
            self._prefix_map.move_to_end(prefix_key)
            while len(self._prefix_map) > PREFIX_MAP_CAP:
                self._prefix_map.popitem(last=False)
        return tag

    async def admission_check(self) -> Optional[dict]:
        """None to admit; a shed dict ({"reason", "retry_after_s",
        "projected_wait_s"}) to reject before touching a replica."""
        await self._refresh()
        slo = getattr(self, "_slo", None)
        if not slo or not self._table:
            return None
        live = self._live_cache()
        await live.refresh_async()
        now = time.time()
        replicas = [(self._inflight.get(t, 0),
                     live.row(self._deployment, t))
                    for t in self._table]
        return live_signals.admission_decision(slo, replicas, now)

    async def submit(self, method: str, args: tuple, kwargs: dict,
                     model_id: Optional[str] = None,
                     with_tag: bool = False,
                     prefix_key: Optional[str] = None,
                     allow_compiled: bool = False,
                     timeout_s: float = 60.0):
        await self._refresh()
        # compiled fast path: one ring write + one ring read, no replica
        # pick, no actor-call RPC. Only plain __call__ shapes ride it —
        # multiplexed models and replica-affine calls (SSE) need the
        # dynamic router's placement. A broken/cold chain falls through
        # to the dynamic path below (the chain ALSO fails items over
        # internally once they were submitted to it).
        chain = self._chain if allow_compiled else None
        if (chain is not None and chain.is_compiled()
                and method == "__call__" and not model_id
                and len(args) == 1 and not kwargs):
            result = await _chain_result(chain.submit(args[0]), timeout_s)
            return (result, None) if with_tag else result
        await self._live_cache().refresh_async()
        # Cold-start path (scale-to-zero): a deployment parked at zero
        # replicas has an empty route table. Queue here — NOT 500 — and
        # push our queue depth to the controller as demand (~1/s): that is
        # the live signal `calculate_desired_num_replicas` wakes on. The
        # deadline covers a replica __init__ (checkpoint/P2P weight load),
        # aligned with the controller's REPLICA_INIT_GRACE_S.
        if not self._table:
            deadline = time.monotonic() + live_signals._flag(
                "serve_cold_start_deadline_s", 120.0)
            self._cold_waiters = getattr(self, "_cold_waiters", 0) + 1
            last_push = 0.0
            try:
                while not self._table:
                    now = time.monotonic()
                    if now > deadline:
                        raise RuntimeError(
                            f"no replicas for {self._deployment}")
                    if now - last_push >= 1.0:
                        last_push = now
                        try:
                            await self._controller.record_handle_metrics \
                                .remote(self._deployment, self._cold_waiters)
                        except Exception:
                            pass    # controller restarting: keep queueing
                    await asyncio.sleep(0.1)
                    await self._refresh(force=True)
            finally:
                self._cold_waiters -= 1
        if model_id:
            kwargs = {**kwargs, "_multiplexed_model_id": model_id}
        excluded: set = set()
        last_err: Optional[BaseException] = None
        for attempt in range(SUBMIT_ATTEMPTS):
            tags = [t for t in self._table if t not in excluded]
            if model_id:
                warm = [t for t in tags
                        if model_id in self._model_map.get(t, [])]
                if warm:
                    tags = warm
            if not tags:
                break
            tag = self._choose(tags, prefix_key)
            try:
                result = await self.submit_on(tag, method, args, kwargs)
                return (result, tag) if with_tag else result
            except Exception as e:  # noqa: BLE001 - classified below
                if not _is_infra_error(e) or attempt == SUBMIT_ATTEMPTS - 1:
                    raise
                # replica died/drained mid-request: fail over to another
                # replica instead of surfacing a 500 for an operation the
                # replica never completed
                last_err = e
                excluded.add(tag)
                self._drop_replica(tag)
                try:
                    _get_serve_metrics()["failover"].inc(
                        tags={"route": self._deployment})
                except Exception:
                    pass
                await self._refresh(force=True)
        raise last_err or RuntimeError(
            f"no live replicas for {self._deployment}")

    async def submit_on(self, tag: str, method: str, args: tuple,
                        kwargs: dict):
        """Call a SPECIFIC replica — SSE streams must pull follow-up
        chunks from the replica that owns the stream state."""
        handle = self._table.get(tag)
        if handle is None:
            raise RuntimeError(f"replica {tag} is gone")
        self._inflight[tag] = self._inflight.get(tag, 0) + 1
        try:
            # .remote() can block on the head for large payloads (object
            # registration); keep it off the event loop. The contextvars
            # copy carries the request's root span into the executor
            # thread, where call_actor injects it toward the replica.
            import contextvars

            loop = asyncio.get_running_loop()
            ctx = contextvars.copy_context()
            ref = await loop.run_in_executor(
                None, lambda: ctx.run(
                    lambda: handle.handle_request.remote(
                        method, args, kwargs)))
            return await ref
        finally:
            self._inflight[tag] = max(0, self._inflight.get(tag, 1) - 1)


@ray_tpu.remote
class ProxyActor:
    """Per-node HTTP ingress. Async actor: aiohttp server on the event loop.

    The controller HANDLE is passed in (never looked up here): proxy code
    runs on the worker's event loop, where blocking client calls would
    deadlock — everything control-plane is awaited.
    """

    def __init__(self, controller_handle):
        self._controller = controller_handle
        self._routes: Dict[str, str] = {}
        self._routers: Dict[str, _AsyncRouter] = {}
        self._routes_ts = 0.0
        self._runner = None
        self.port: Optional[int] = None

    def _get_controller(self):
        return self._controller

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self.port

    async def _refresh_routes(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._routes_ts < ROUTE_REFRESH_S:
            return
        self._routes = await self._get_controller().get_routes.remote()
        self._routes_ts = now

    async def _handle(self, request):
        """Telemetry wrapper: one root span per request (honoring an
        incoming W3C `traceparent`, so a client-supplied trace id follows
        the request into the replica) + `serve_request_seconds` by
        matched route and status code."""
        from ray_tpu.util import tracing

        t0 = time.perf_counter()
        tp = request.headers.get("traceparent")
        with tracing.request_span(
                "http.request",
                {"traceparent": tp} if tp else None,
                attributes={"ray_tpu.op": "serve_request",
                            "http.method": request.method,
                            "http.path": "/" + request.match_info["tail"]}
                ) as span:
            resp = await self._handle_routed(request)
            if span is not None:
                span.attributes["http.status"] = resp.status
        route = request.get("rtpu_route") or "(no_route)"
        try:
            _get_serve_metrics()["request_seconds"].observe(
                time.perf_counter() - t0,
                tags={"route": route, "code": str(resp.status)})
        except Exception:
            pass
        return resp

    async def _handle_routed(self, request):
        from aiohttp import web

        await self._refresh_routes()
        path = "/" + request.match_info["tail"]
        match = None
        for prefix in sorted(self._routes, key=len, reverse=True):
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") \
                    or prefix == "/":
                match = prefix
                break
        if match is None:
            await self._refresh_routes(force=True)
            for prefix in sorted(self._routes, key=len, reverse=True):
                if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                    match = prefix
                    break
        if match is None:
            return web.json_response({"error": f"no route for {path}"},
                                     status=404)
        request["rtpu_route"] = match
        deployment = self._routes[match]
        router = self._routers.get(deployment)
        if router is None:
            router = self._routers[deployment] = _AsyncRouter(
                self._get_controller(), deployment)
        # SLO-aware admission control: shed BEFORE reading the body into
        # a replica call — an overloaded route answers 429 + Retry-After
        # from the proxy alone (reference: Serve's backpressure returns
        # 503; 429 matches the retryable-client contract here)
        try:
            shed = await router.admission_check()
        except Exception:
            shed = None     # a broken signal plane must not block ingress
        retry_after = note_admission(match, shed)
        if shed is not None:
            return web.json_response(
                {"error": "deployment over capacity",
                 "reason": shed["reason"],
                 "projected_wait_s": shed.get("projected_wait_s")},
                status=429, headers={"Retry-After": str(retry_after)})
        body = await request.read()
        try:
            json_body = await request.json() if body else None
        except Exception:
            json_body = None
        req = Request(request.method, path, dict(request.query),
                      dict(request.headers), body, json_body)
        model_id = request.headers.get("serve_multiplexed_model_id")
        # streaming responses are replica-affine (stream_next follow-ups
        # must hit the replica holding the stream) — keep them dynamic
        stream = bool(isinstance(json_body, dict) and json_body.get("stream"))
        try:
            result, tag = await router.submit(
                "__call__", (req,), {}, model_id=model_id, with_tag=True,
                prefix_key=prompt_prefix_key(json_body),
                allow_compiled=not stream)
        except Exception as e:  # noqa: BLE001 - surface as HTTP 500
            return web.json_response({"error": repr(e)}, status=500)
        if isinstance(result, dict) and "__sse_stream__" in result:
            if tag is None:
                # compiled path can't anchor a replica-affine stream; the
                # deployment opened one for a body without stream=true
                return web.json_response(
                    {"error": "streaming response requires "
                              '"stream": true in the request body'},
                    status=400)
            return await self._stream_sse(request, router, tag,
                                          result["__sse_stream__"])
        if isinstance(result, web.Response):
            return result
        if isinstance(result, (dict, list)):
            return web.json_response(result)
        if isinstance(result, bytes):
            return web.Response(body=result)
        return web.Response(text=str(result))

    async def _stream_sse(self, request, router: _AsyncRouter, tag: str,
                          info: dict):
        """OpenAI `stream: true` transport: pull incremental tokens from
        the owning replica and relay them as server-sent events, ending
        with `data: [DONE]` (reference serve.llm streaming router)."""
        import json as _json

        from aiohttp import web

        import uuid

        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive"})
        await resp.prepare(request)
        sid = info["stream_id"]
        chat = info.get("mode") == "chat"
        created = int(time.time())
        # one id for every chunk of the stream (OpenAI SDKs require it)
        rid = (f"chatcmpl-{uuid.uuid4().hex[:24]}" if chat
               else f"cmpl-{uuid.uuid4().hex[:24]}")
        cursor = 0
        last_progress = time.monotonic()
        try:
            while True:
                chunk = await router.submit_on(
                    tag, "stream_next", (sid,), {"cursor": cursor})
                if chunk.get("error"):
                    await resp.write(
                        f"data: {_json.dumps({'error': chunk['error']})}"
                        f"\n\n".encode())
                    await resp.write(b"data: [DONE]\n\n")
                    break
                cursor = chunk.get("cursor", cursor)
                done = chunk.get("done", False)
                if not chunk["token_ids"] and not done:
                    # queued behind a full slot batch: bounded patience,
                    # then a clean error instead of an immortal stream
                    if time.monotonic() - last_progress > 120:
                        await resp.write(
                            b'data: {"error": "generation stalled"}\n\n'
                            b"data: [DONE]\n\n")
                        break
                    continue
                last_progress = time.monotonic()
                # chunk["text"] is the server-computed DELTA (derived from
                # a cumulative decode, so multi-byte chars never split)
                delta_text = chunk["text"]
                if not delta_text and not done:
                    # tokens arrived but decoded to nothing yet (the
                    # server holds back a partial multi-byte char): the
                    # text rides the next decodable delta, so emitting an
                    # empty chunk here is pure noise — and makes the
                    # first-chunk-has-content property timing-dependent
                    continue
                finish = chunk.get("finish_reason") if done else None
                if chat:
                    payload = {
                        "id": rid, "object": "chat.completion.chunk",
                        "created": created, "model": info["model"],
                        "choices": [{"index": 0,
                                     "delta": ({"content": delta_text}
                                               if delta_text else {}),
                                     "finish_reason": finish}]}
                else:
                    payload = {
                        "id": rid, "object": "text_completion",
                        "created": created, "model": info["model"],
                        "choices": [{"index": 0, "text": delta_text,
                                     "finish_reason": finish}]}
                await resp.write(
                    f"data: {_json.dumps(payload)}\n\n".encode())
                if done:
                    await resp.write(b"data: [DONE]\n\n")
                    break
        except (ConnectionResetError, asyncio.CancelledError):
            pass  # client went away; the replica GC's the stream by TTL
        except Exception as e:  # noqa: BLE001 - headers already sent:
            # the failure must arrive as an SSE event, not a TCP reset
            # (replica restarted mid-stream, stream id lost, ...)
            try:
                await resp.write(
                    f"data: {_json.dumps({'error': repr(e)})}\n\n"
                    f"data: [DONE]\n\n".encode())
            except Exception:
                pass
        await resp.write_eof()
        return resp

    async def ready(self) -> int:
        return self.port

    # ------------------------------------------------------- test support
    async def chain_status(self, deployment: str) -> dict:
        """Compiled-ingress introspection (tests, `ray-tpu top` drills):
        whether the deployment's chain is live, its per-lane replica
        spread and lifetime counters."""
        router = self._routers.get(deployment)
        if router is None:
            return {"compiled": False, "chain": False}
        # a status poll also advances the (rate-limited) table refresh:
        # an operator watching a degraded chain drives the re-spread
        # check even when the deployment is idle
        try:
            await router._refresh()
        except Exception:
            pass
        return router.chain_status()

    async def rpc_audit_start(self) -> bool:
        """Head-RPC audit between start/stop, recorded INSIDE the proxy
        process (the zero-control-plane-RPCs-per-warm-request contract is
        interposer-verified where the ingress actually runs)."""
        if not hasattr(self, "_audit"):
            from ray_tpu.serve.disagg import _RpcAudit

            self._audit = _RpcAudit()
        return self._audit.start()

    async def rpc_audit_stop(self) -> list:
        if not hasattr(self, "_audit"):
            return []
        return self._audit.stop()

    async def stop(self):
        # chain shutdown joins worker threads — keep it off the loop
        loop = asyncio.get_running_loop()
        for router in list(self._routers.values()):
            try:
                await asyncio.wait_for(
                    loop.run_in_executor(None, router.shutdown_chain), 20)
            except Exception:
                pass
        if self._runner is not None:
            await self._runner.cleanup()
        return True
