"""Paged KV-cache block pool with prompt-prefix reuse.

Behavioral parity with the reference's vLLM-side paged KV + prefix
caching surfaces (`python/ray/llm/_internal/serve/request_router/
prefix_aware/prefix_aware_router.py:39` routes on them; vLLM owns the
block table): KV state is stored in fixed-size token blocks addressed by
a rolling content hash of the prompt prefix, so requests sharing a
prefix skip prefill for the cached span and shared prefixes are stored
ONCE.

TPU-first shape choice: the pool is a dense jax array
`[n_layer, n_blocks, n_head, block_size, head_dim]` and reuse happens by
block-granular device-to-device copies into the decode engine's dense
per-slot cache (XLA-friendly static shapes; dynamic_update_slice on
block boundaries). In-kernel gather-paging is a Pallas follow-up; the
bookkeeping, hashing, eviction, and dedup semantics here are the real
thing.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple


def _chain_hash(prev: bytes, token_block: Tuple[int, ...]) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(prev)
    h.update(repr(token_block).encode())
    return h.digest()


def chain_hashes(ids: List[int], block_size: int) -> List[Tuple[bytes, int]]:
    """Rolling content hashes of every FULL block boundary of a prompt:
    [(hash_of_blocks_1..k, k*block_size), ...]. This is THE content
    address of a prefix — the same function keys the local block table,
    the cluster prefix store, and the routing residency hints, so a hash
    computed anywhere matches a prefix computed anywhere else."""
    out: List[Tuple[bytes, int]] = []
    h = b"root"
    for i in range(0, len(ids) - len(ids) % block_size, block_size):
        h = _chain_hash(h, tuple(ids[i:i + block_size]))
        out.append((h, i + block_size))
    return out


class PagedKVCache:
    """Host-side block table + device-side block pool.

    match_prefix(ids)  -> (n_cached_tokens, [block ids]) — longest chain
                          of full blocks whose content hashes are pooled.
    store_prefix(...)  -> copy a finished prompt's full blocks from a
                          slot's dense cache into the pool (dedup'd).
    copy_into_slot(...)-> materialize matched blocks into a slot cache.
    """

    def __init__(self, n_layer: int, n_head: int, head_dim: int,
                 num_blocks: int = 64, block_size: int = 16,
                 dtype=None):
        import jax
        import jax.numpy as jnp

        self.jax, self.jnp = jax, jnp
        self.block_size = block_size
        self.num_blocks = num_blocks
        shape = (n_layer, num_blocks, n_head, block_size, head_dim)
        dtype = dtype or jnp.float32
        self.pool_k = jnp.zeros(shape, dtype)
        self.pool_v = jnp.zeros(shape, dtype)
        self._free: List[int] = list(range(num_blocks))
        # chain hash -> block id, LRU order (least recent first)
        self._table: "OrderedDict[bytes, int]" = OrderedDict()
        self._hash_of_block: Dict[int, bytes] = {}
        # counters (tests + /stats)
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.blocks_evicted = 0

        L, N, H, Bs, Dh = shape

        def _copy_out(pool, cache, slot, t0, blk):
            data = jax.lax.dynamic_slice(
                cache, (0, slot, 0, t0, 0), (L, 1, H, Bs, Dh))
            return jax.lax.dynamic_update_slice(
                pool, data.reshape(L, 1, H, Bs, Dh), (0, blk, 0, 0, 0))

        def _copy_in(cache, pool, slot, t0, blk):
            data = jax.lax.dynamic_slice(
                pool, (0, blk, 0, 0, 0), (L, 1, H, Bs, Dh))
            return jax.lax.dynamic_update_slice(
                cache, data, (0, slot, 0, t0, 0))

        self._copy_out = jax.jit(_copy_out, donate_argnums=(0,))
        self._copy_in = jax.jit(_copy_in, donate_argnums=(0,))

    # ------------------------------------------------------------ hashing
    def _chains(self, ids: List[int]):
        """Yield (chain_hash, token_block) for every FULL block of ids —
        delegates to `chain_hashes` so the local block table and the
        cluster prefix store can never disagree on a content address."""
        B = self.block_size
        for h, n in chain_hashes(ids, B):
            yield h, tuple(ids[n - B:n])

    # ------------------------------------------------------------- lookup
    def peek_prefix_len(self, ids: List[int]) -> int:
        """Cached-token count for `ids`' prefix WITHOUT touching the LRU
        order or the hit/miss counters — the disagg decode side uses this
        to decide whether fetching remote KV would gain anything before
        it commits to a prefill RPC."""
        n = 0
        for h, _blk in self._chains(ids):
            if h not in self._table:
                break
            n += self.block_size
        return n

    def recent_chain_hashes(self, n: int = 48) -> List[bytes]:
        """The most-recently-touched pooled chain hashes (LRU tail) —
        what this engine advertises as its resident-prefix routing hint."""
        return list(self._table)[-n:]

    def match_prefix(self, ids: List[int]) -> Tuple[int, List[int]]:
        blocks: List[int] = []
        for h, _blk in self._chains(ids):
            blk_id = self._table.get(h)
            if blk_id is None:
                break
            self._table.move_to_end(h)       # LRU touch
            blocks.append(blk_id)
        n = len(blocks) * self.block_size
        if blocks:
            self.hits += 1
            self.tokens_reused += n
        else:
            self.misses += 1
        return n, blocks

    # ----------------------------------------------------------- eviction
    def _alloc(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        if not self._table:
            return None
        # evict the least-recently-matched chain entry. A child whose
        # parent is evicted can never match again (match walks from the
        # root) and ages out the same way.
        _h, blk = self._table.popitem(last=False)
        self._hash_of_block.pop(blk, None)
        self.blocks_evicted += 1
        return blk

    # -------------------------------------------------------------- store
    def store_prefix(self, ids: List[int], cache, slot: int) -> int:
        """Copy every full block of `ids` from `cache`'s dense slot lane
        into the pool (skipping chains already present). Returns the
        number of NEW blocks stored. `cache` is the engine's {"k","v"}."""
        stored = 0
        t0 = 0
        for h, _blk in self._chains(ids):
            if h not in self._table:
                blk = self._alloc()
                if blk is None:
                    break
                self.pool_k = self._copy_out(self.pool_k, cache["k"],
                                             slot, t0, blk)
                self.pool_v = self._copy_out(self.pool_v, cache["v"],
                                             slot, t0, blk)
                self._table[h] = blk
                self._hash_of_block[blk] = h
                stored += 1
            else:
                self._table.move_to_end(h)
            t0 += self.block_size
        return stored

    # --------------------------------------------------------------- load
    def copy_into_slot(self, cache, slot: int, blocks: List[int]):
        """Materialize matched pool blocks into cache slot lane starting
        at position 0; returns the updated cache dict."""
        k, v = cache["k"], cache["v"]
        t0 = 0
        for blk in blocks:
            k = self._copy_in(k, self.pool_k, slot, t0, blk)
            v = self._copy_in(v, self.pool_v, slot, t0, blk)
            t0 += self.block_size
        return {"k": k, "v": v}

    # -------------------------------------------------------------- stats
    def stats(self) -> dict:
        return {"blocks_total": self.num_blocks,
                "blocks_used": self.num_blocks - len(self._free),
                "block_size": self.block_size,
                "prefix_hits": self.hits, "prefix_misses": self.misses,
                "tokens_reused": self.tokens_reused,
                "blocks_evicted": self.blocks_evicted}


# ----------------------------------------------------- KV transfer (P/D)
# Reference: serve.llm KV-transfer connectors (`llm/_internal/serve/...
# nixl_connector.py`, lmcache) — ship computed prefix KV between
# replicas so a PREFILL fleet feeds a DECODE fleet. Here blocks are jax
# arrays, so the wire format is a plain numpy blob dict that can ride
# the object store / an ObjectRef between actors.

def export_prefix(kv: "PagedKVCache", ids) -> Optional[dict]:
    """Serialize the pooled KV blocks covering `ids`' prefix into a
    host-memory blob: {"ids", "k", "v"} with k/v [n_blocks, L, H, Bs, Dh].
    Returns None when nothing is pooled for this prompt.

    NOTE: blobs that serialize below the object store's inline threshold
    (core/store.py INLINE_THRESHOLD, 100 KiB) are NEVER published to the
    cluster prefix store — inline objects ride actor replies, not the
    sealed-object plane, so a directory binding could not serve a P2P
    pull. Tiny models / very short prefixes fall below it; the skip is
    counted as `prefix_store_inline_skipped_total` on /metrics."""
    import numpy as np

    n, blocks = kv.match_prefix(list(ids))
    if not blocks:
        return None
    k = np.stack([np.asarray(
        kv.jax.lax.dynamic_index_in_dim(kv.pool_k, b, 1, keepdims=False))
        for b in blocks])
    v = np.stack([np.asarray(
        kv.jax.lax.dynamic_index_in_dim(kv.pool_v, b, 1, keepdims=False))
        for b in blocks])
    return {"ids": list(ids[:n]), "k": k, "v": v,
            "block_size": kv.block_size}


def import_prefix(kv: "PagedKVCache", blob: dict) -> int:
    """Install an exported prefix into THIS pool (dedup'd against what's
    already cached). Returns the number of new blocks installed."""
    if not blob:
        return 0
    if blob["block_size"] != kv.block_size:
        raise ValueError(
            f"block_size mismatch: {blob['block_size']} != {kv.block_size}")
    jnp = kv.jnp
    installed = 0
    for i, (h, _blk) in enumerate(kv._chains(blob["ids"])):
        if h in kv._table:
            kv._table.move_to_end(h)
            continue
        blk = kv._alloc()
        if blk is None:
            break
        kb = jnp.asarray(blob["k"][i])[:, None]   # [L,1,H,Bs,Dh]
        vb = jnp.asarray(blob["v"][i])[:, None]
        kv.pool_k = kv.jax.lax.dynamic_update_slice(
            kv.pool_k, kb.astype(kv.pool_k.dtype), (0, blk, 0, 0, 0))
        kv.pool_v = kv.jax.lax.dynamic_update_slice(
            kv.pool_v, vb.astype(kv.pool_v.dtype), (0, blk, 0, 0, 0))
        kv._table[h] = blk
        kv._hash_of_block[blk] = h
        installed += 1
    return installed
