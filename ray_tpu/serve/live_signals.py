"""Live-signal routing + SLO admission control over gossiped replica load.

The workload flight recorder already gossips every serve replica's
queue depth / in-flight / EWMA latency to the head (zero new RPCs:
`ray_tpu.util.metrics.publish_workload` rides the metrics-push channel,
merged into `state.list_serve_stats()`). This module is the consumer
side of that plane:

- `LiveLoadCache` — a per-process, TTL-refreshed view of the merged
  rows, shared by the HTTP proxy, the gRPC proxy, `DeploymentHandle`,
  and the serve controller's autoscaler.
- `replica_score` — the effective queue depth a router compares in its
  pow-2 choice: the gossiped queue when fresh (each router only sees its
  OWN in-flight; the gossiped row sees the replica's true admitted
  load), blended with the local count so a burst this router just sent
  is never invisible.
- `SLOConfig` + `admission_decision` — SLO-aware bounded queues at the
  ingress: shed (HTTP 429 / gRPC RESOURCE_EXHAUSTED, with Retry-After)
  when every replica's queue is at the bound or when the EWMA-projected
  wait of the BEST replica already exceeds the route's SLO.

The policy functions are pure (load rows in, decision out) so they are
unit-testable without a cluster.
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
import threading
import time
from typing import Dict, List, Optional, Tuple


def _flag(name: str, default: float) -> float:
    try:
        from ray_tpu.core import config as _config

        return float(_config.get(name))
    except Exception:
        return default


@dataclasses.dataclass
class SLOConfig:
    """Per-deployment admission policy (rides the routing table to every
    ingress). `slo_s`: shed when the best replica's EWMA-projected wait
    exceeds this (0 disables). `max_queue`: shed when every replica's
    effective queue depth reaches this bound (0 = unbounded).
    `retry_after_s`: floor for the Retry-After hint on sheds."""

    slo_s: float = 0.0
    max_queue: int = 0
    retry_after_s: float = 1.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def as_slo(value) -> Optional[SLOConfig]:
    if value is None:
        return None
    if isinstance(value, SLOConfig):
        return value
    if isinstance(value, dict):
        return SLOConfig(**value)
    raise TypeError(f"slo_config must be SLOConfig or dict, got {value!r}")


class LiveLoadCache:
    """TTL-cached view of the gossiped serve-replica load rows, keyed
    deployment -> replica tag. Refresh failures are swallowed (routers
    must keep routing on local counts through a head outage)."""

    def __init__(self, refresh_s: Optional[float] = None):
        self._refresh_s = refresh_s
        self._rows: Dict[str, Dict[str, dict]] = {}
        self._ts = 0.0
        self._lock = threading.Lock()

    def _period(self) -> float:
        if self._refresh_s is not None:
            return self._refresh_s
        return _flag("serve_live_signal_refresh_s", 1.0)

    def refresh(self, force: bool = False) -> None:
        period = self._period()
        if period <= 0:
            return                    # live-signal consumption disabled
        now = time.monotonic()
        with self._lock:
            if not force and now - self._ts < period:
                return
            self._ts = now            # claim the slot even on failure
        rows = self._gossiped_rows()
        if rows is None:
            # no broadcast-fed view in this process (remote driver, serve
            # plane not yet announced): fall back to one state-API pull —
            # but only inside an already-initialized runtime. The state
            # client AUTO-INITS a default single-node runtime otherwise,
            # and a router consulted pre-init (unit tests, standalone
            # tooling) must not leave that runtime behind to starve the
            # cluster a later ray_tpu.init() actually wants.
            try:
                from ray_tpu.core import api as core_api

                if not core_api.is_initialized():
                    return
                from ray_tpu.util import state

                rows = state.list_serve_stats(
                    filters=[("kind", "=", "serve_replica")])
            except Exception:
                return
        merged: Dict[str, Dict[str, dict]] = {}
        for r in rows:
            st = r.get("stats") or {}
            dep = st.get("deployment")
            if not dep:
                continue
            merged.setdefault(dep, {})[r.get("key")] = {
                **st, "ts": r.get("ts", 0.0)}
        with self._lock:
            self._rows = merged

    @staticmethod
    def _gossiped_rows() -> Optional[list]:
        """Serve-load rows adopted from the cluster_view broadcast: the
        zero-RPC primary source (the head piggybacks changed rows on the
        snapshots every subscribed process already receives). None when
        this process has never adopted a row batch."""
        try:
            from ray_tpu.core import api as core_api

            if not core_api.is_initialized():
                return None
            return core_api._global_client().cluster_view.serve_loads
        except Exception:
            return None

    async def refresh_async(self, force: bool = False) -> None:
        """Event-loop-safe refresh: the state call is a blocking head
        round trip, so it runs on the default executor."""
        period = self._period()
        if period <= 0:
            return
        if not force and time.monotonic() - self._ts < period:
            return
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, lambda: self.refresh(force))

    def snapshot(self) -> Dict[str, Dict[str, dict]]:
        with self._lock:
            return {dep: dict(rows) for dep, rows in self._rows.items()}

    def rows_for(self, deployment: str) -> Dict[str, dict]:
        with self._lock:
            return dict(self._rows.get(deployment, {}))

    def row(self, deployment: str, tag: str) -> Optional[dict]:
        with self._lock:
            return self._rows.get(deployment, {}).get(tag)


_cache: Optional[LiveLoadCache] = None
_cache_lock = threading.Lock()


def get_cache() -> LiveLoadCache:
    """Process-wide cache: the proxy's routers, handles, and the
    controller share one refresh cadence per process."""
    global _cache
    with _cache_lock:
        if _cache is None:
            _cache = LiveLoadCache()
        return _cache


# ------------------------------------------------------------ pure policy
def replica_score(local_inflight: int, row: Optional[dict], now: float,
                  max_age_s: float) -> float:
    """Effective queue depth for routing/admission: the gossiped row when
    fresh (it sees ALL routers' traffic), never below the local count
    (this router's just-sent burst hasn't been gossiped yet)."""
    if row and now - (row.get("ts") or 0.0) <= max_age_s:
        return max(float(local_inflight), float(row.get("queue_depth") or 0))
    return float(local_inflight)


def ewma_of(row: Optional[dict]) -> float:
    """EWMA service latency of a replica row; unlike queue depth it does
    not decay with row age (an idle replica's last measured service time
    is still the best estimate)."""
    return float((row or {}).get("ewma_latency_s") or 0.0)


def prefix_match_len(row: Optional[dict], chain_hexes, now: float,
                     max_age_s: float) -> int:
    """Longest-matching-prefix depth a replica's gossiped load row
    advertises for a prompt: `chain_hexes` is the prompt's rolling chain
    hashes in prefix order (hex), the row's `prefix_roots` the replica's
    resident set. Stale rows (including those of departed replicas whose
    last row still lingers in the cache) advertise NOTHING — a dead
    replica's residency must never attract traffic."""
    if not chain_hexes or not row:
        return 0
    if now - (row.get("ts") or 0.0) > max_age_s:
        return 0
    roots = row.get("prefix_roots")
    if not roots:
        return 0
    roots = set(roots)
    best = 0
    for i, h in enumerate(chain_hexes):
        if h in roots:
            best = i + 1
    return best


def pick_prefix_affinity(tags, chain_hexes, row_of, score_of, now: float,
                         max_age_s: float,
                         max_imbalance: float = 8.0) -> Optional[object]:
    """Prefix-affinity replica pick: the tag whose fresh row advertises
    the deepest resident match for the prompt (queue score breaks ties —
    among equally-warm replicas the shorter queue wins). A warm replica
    whose queue runs `max_imbalance` past the least-loaded candidate is
    excluded — the sole replica holding a popular prefix must not absorb
    the whole workload while peers idle; past that point recomputing the
    prefix on an idle replica is cheaper than waiting. None when no
    (eligible) replica advertises any match, so the caller falls back to
    pow-2 on load alone."""
    scores = {t: score_of(t) for t in tags}
    if not scores:
        return None
    min_score = min(scores.values())
    best_tag, best_key = None, None
    for t in tags:
        if scores[t] - min_score > max_imbalance:
            continue   # overloaded vs an idle peer: not a candidate
        depth = prefix_match_len(row_of(t), chain_hexes, now, max_age_s)
        if depth <= 0:
            continue
        key = (-depth, scores[t])
        if best_key is None or key < best_key:
            best_tag, best_key = t, key
    return best_tag


def pick_pow2(tags, score_of, ewma_of_tag) -> object:
    """Power-of-two-choices over live scores with an EWMA-latency
    tiebreak — the shared core of the proxy router's and
    DeploymentHandle's replica pick. `score_of`/`ewma_of_tag` map a tag
    to its effective queue depth / service EWMA."""
    if len(tags) == 1:
        return tags[0]
    a, b = random.sample(list(tags), 2)
    sa, sb = score_of(a), score_of(b)
    if sa == sb:
        return a if ewma_of_tag(a) <= ewma_of_tag(b) else b
    return a if sa < sb else b


def admission_decision(slo, replicas: List[Tuple[int, Optional[dict]]],
                       now: Optional[float] = None,
                       max_age_s: Optional[float] = None) -> Optional[dict]:
    """Admit (None) or shed ({"reason", "retry_after_s",
    "projected_wait_s"}) one ingress request.

    `replicas`: [(local_inflight, gossiped_row_or_None)] for the route's
    current replica set. Sheds when every replica's effective queue is at
    `max_queue`, or when even the best replica's EWMA-projected wait
    (service EWMA x queued-ahead+1) exceeds `slo_s`.
    """
    slo = as_slo(slo)
    if slo is None or not replicas or (slo.slo_s <= 0 and slo.max_queue <= 0):
        return None
    now = time.time() if now is None else now
    if max_age_s is None:
        max_age_s = _flag("serve_live_signal_max_age_s", 5.0)
    scored = [(replica_score(local, row, now, max_age_s), row)
              for local, row in replicas]
    best_queue = min(q for q, _ in scored)
    if slo.max_queue > 0 and best_queue >= slo.max_queue:
        return {"reason": "queue_full",
                "retry_after_s": slo.retry_after_s,
                "projected_wait_s": None}
    if slo.slo_s > 0:
        projections = [ewma_of(row) * (q + 1.0) for q, row in scored]
        best = min(projections)
        if best > slo.slo_s:
            return {"reason": "slo",
                    "retry_after_s": max(slo.retry_after_s,
                                         round(best - slo.slo_s, 2)),
                    "projected_wait_s": round(best, 4)}
    return None
