"""Serve autoscaling policy.

Parity with `python/ray/serve/autoscaling_policy.py:13
_calculate_desired_num_replicas` + AutoscalingConfig fields
(`serve/config.py:186` target_ongoing_requests, min/max_replicas,
upscale/downscale smoothing).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional


@dataclasses.dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    target_ongoing_requests: float = 2.0
    upscale_smoothing_factor: float = 1.0
    downscale_smoothing_factor: float = 1.0
    look_back_period_s: float = 2.0


def calculate_desired_num_replicas(config: AutoscalingConfig,
                                   total_ongoing_requests: float,
                                   current_num_replicas: int) -> int:
    if current_num_replicas == 0:
        return max(config.min_replicas, 1)
    per_replica = total_ongoing_requests / current_num_replicas
    error_ratio = per_replica / max(config.target_ongoing_requests, 1e-9)
    if error_ratio > 1:
        smoothed = 1 + (error_ratio - 1) * config.upscale_smoothing_factor
        desired = math.ceil(current_num_replicas * smoothed)
    else:
        smoothed = 1 - (1 - error_ratio) * config.downscale_smoothing_factor
        desired = math.floor(current_num_replicas * smoothed)
        desired = max(desired, 1) if total_ongoing_requests > 0 else desired
    return int(min(max(desired, config.min_replicas), config.max_replicas))
