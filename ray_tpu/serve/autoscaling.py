"""Serve autoscaling policy.

Parity with `python/ray/serve/autoscaling_policy.py:13
_calculate_desired_num_replicas` + AutoscalingConfig fields
(`serve/config.py:186` target_ongoing_requests, min/max_replicas,
upscale/downscale smoothing).

`desired_from_live_load` is the serving-plane upgrade: the controller
feeds the calculation from the GOSSIPED replica load rows (queue depth +
EWMA latency via `state.list_serve_stats()`) rather than its own
health-check-polled counts, so scale-up reacts at gossip latency. It
returns None when there's no fresh signal and the caller falls back to
the polled path.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import List, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    target_ongoing_requests: float = 2.0
    upscale_smoothing_factor: float = 1.0
    downscale_smoothing_factor: float = 1.0
    look_back_period_s: float = 2.0
    # live-signal knobs: a fresh gossiped row is one younger than
    # signal_staleness_s; target_latency_s > 0 additionally scales up
    # when per-replica EWMA latency exceeds the target (0 disables)
    signal_staleness_s: float = 10.0
    target_latency_s: float = 0.0


def calculate_desired_num_replicas(config: AutoscalingConfig,
                                   total_ongoing_requests: float,
                                   current_num_replicas: int) -> int:
    if current_num_replicas == 0:
        # Scale-to-zero: a parked deployment (explicit min_replicas=0)
        # stays at zero until demand shows up — the proxy pushes its
        # queue depth as ongoing requests, which wakes exactly one
        # replica; the normal error-ratio path grows it from there.
        # min_replicas>=1 keeps the historical always-on floor.
        if total_ongoing_requests > 0:
            return max(config.min_replicas, 1)
        return max(config.min_replicas, 0)
    per_replica = total_ongoing_requests / current_num_replicas
    error_ratio = per_replica / max(config.target_ongoing_requests, 1e-9)
    if error_ratio > 1:
        smoothed = 1 + (error_ratio - 1) * config.upscale_smoothing_factor
        desired = math.ceil(current_num_replicas * smoothed)
    else:
        smoothed = 1 - (1 - error_ratio) * config.downscale_smoothing_factor
        desired = math.floor(current_num_replicas * smoothed)
        desired = max(desired, 1) if total_ongoing_requests > 0 else desired
    return int(min(max(desired, config.min_replicas), config.max_replicas))


def desired_from_live_load(config: AutoscalingConfig, rows: List[dict],
                           current_num_replicas: int,
                           now: Optional[float] = None) -> Optional[int]:
    """Desired replica count from gossiped live-load rows for ONE
    deployment ({"queue_depth", "ewma_latency_s", "ts", ...} per
    replica). Queue depth drives the ongoing-requests error ratio;
    `target_latency_s` adds a proportional scale-up floor when a
    replica's PROJECTED QUEUEING WAIT (service EWMA x queued requests)
    exceeds the target (capped at 4x per pass so one bad sample can't
    explode the fleet). The boost deliberately uses projected wait, not
    raw service time: a handler whose base latency exceeds the target
    would otherwise ratchet the fleet to max_replicas and pin it there —
    more replicas can shorten queues, never the service time itself.
    Returns None when no row is fresh — rows only refresh as requests
    flow, so an idle deployment deliberately falls back to the
    controller-polled (low) counts and scales down."""
    now = time.time() if now is None else now
    fresh = [r for r in rows
             if now - (r.get("ts") or 0.0) <= config.signal_staleness_s]
    if not fresh:
        return None
    total_queue = float(sum(r.get("queue_depth") or 0 for r in fresh))
    desired = calculate_desired_num_replicas(config, total_queue,
                                             current_num_replicas)
    if config.target_latency_s > 0:
        worst = max((r.get("ewma_latency_s") or 0.0)
                    * float(r.get("queue_depth") or 0) for r in fresh)
        if worst > config.target_latency_s:
            boost = math.ceil(current_num_replicas
                              * min(worst / config.target_latency_s, 4.0))
            desired = max(desired, boost)
    return int(min(max(desired, config.min_replicas), config.max_replicas))
