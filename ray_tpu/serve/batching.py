"""@serve.batch: dynamic request batching inside a replica.

Parity with `python/ray/serve/batching.py`: calls block until a batch fills
(max_batch_size) or times out (batch_wait_timeout_s); the wrapped function
receives a list of requests and returns a list of results. Implemented with
a background batching thread (replica methods run on an actor thread pool,
so concurrent callers park on per-request events).

On TPU this is the latency/throughput lever for serving: batched requests
become one padded XLA call instead of N small ones.
"""

from __future__ import annotations

import functools
import queue
import threading
from typing import Any, Callable, List, Optional


class _BatchItem:
    __slots__ = ("args", "event", "result", "error")

    def __init__(self, args):
        self.args = args
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self.queue: "queue.Queue[_BatchItem]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._loop, daemon=True,
                                                name="serve-batcher")
                self._thread.start()

    def _loop(self):
        while True:
            batch = [self.queue.get()]
            deadline = self.timeout
            while len(batch) < self.max_batch_size:
                try:
                    batch.append(self.queue.get(timeout=deadline))
                except queue.Empty:
                    break
            try:
                results = self.fn([item.args for item in batch])
                if len(results) != len(batch):
                    raise ValueError(
                        f"batched fn returned {len(results)} results for a "
                        f"batch of {len(batch)}")
                for item, r in zip(batch, results):
                    item.result = r
            except BaseException as e:  # noqa: BLE001 - fan error to callers
                for item in batch:
                    item.error = e
            for item in batch:
                item.event.set()

    def submit(self, args) -> Any:
        self._ensure_thread()
        item = _BatchItem(args)
        self.queue.put(item)
        item.event.wait()
        if item.error is not None:
            raise item.error
        return item.result


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: `fn(self, items: list) -> list`; callers pass one item."""

    def deco(fn):
        attr = f"__batcher_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(self, item):
            batcher = getattr(self, attr, None)
            if batcher is None:
                batcher = _Batcher(lambda items: fn(self, items),
                                   max_batch_size, batch_wait_timeout_s)
                setattr(self, attr, batcher)
            return batcher.submit(item)

        wrapper._is_serve_batch = True
        return wrapper

    return deco(_fn) if _fn is not None else deco
