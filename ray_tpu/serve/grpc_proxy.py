"""gRPC ingress for Serve deployments.

Parity: the reference's gRPC proxy (`serve/_private/proxy.py` gRPC path +
`grpc_util.py`). Uses grpc's generic RPC handlers, so no protoc codegen is
required: one service `ray_tpu.serve.ServeAPIService` with method `Call`;
request/response payloads are JSON bytes, the target application is picked
with the `application` metadata key (falls back to the route table's root
app). Typed-proto users can layer their own stubs on the same port.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

import ray_tpu

SERVICE = "ray_tpu.serve.ServeAPIService"


ROUTE_REFRESH_S = 1.0   # same cadence as the HTTP proxy


class _GrpcServer:
    def __init__(self, controller):
        self._controller = controller
        self._routers = {}
        self._routes = {}
        self._routes_ts = 0.0
        self._server = None

    async def _refresh_routes(self, force: bool = False) -> None:
        now = time.monotonic()
        if force or not self._routes or now - self._routes_ts > ROUTE_REFRESH_S:
            self._routes = await self._controller.get_routes.remote()
            self._routes_ts = now

    async def _route_for(self, app_name: Optional[str]) -> Optional[str]:
        await self._refresh_routes()
        if app_name:
            for _prefix, dep in self._routes.items():
                if dep == app_name:
                    return dep
            await self._refresh_routes(force=True)
            for _prefix, dep in self._routes.items():
                if dep == app_name:
                    return dep
            return None
        if "/" in self._routes:
            return self._routes["/"]
        return next(iter(self._routes.values()), None)

    async def start(self, port: int = 0) -> int:
        import grpc

        from ray_tpu.serve.proxy import Request, _AsyncRouter

        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if not handler_call_details.method.startswith(f"/{SERVICE}/"):
                    return None
                metadata = dict(handler_call_details.invocation_metadata or ())

                async def call(request_bytes, context):
                    from ray_tpu.serve.proxy import (_get_serve_metrics,
                                                     prompt_prefix_key)
                    from ray_tpu.util import tracing

                    try:
                        body = json.loads(request_bytes) if request_bytes else None
                    except json.JSONDecodeError:
                        body = None
                    app = metadata.get("application")
                    dep = await outer._route_for(app)
                    if dep is None:
                        await context.abort(
                            grpc.StatusCode.NOT_FOUND,
                            f"no deployment for application {app!r}")
                    router = outer._routers.get(dep)
                    if router is None:
                        router = outer._routers[dep] = _AsyncRouter(
                            outer._controller, dep)
                    # SLO-aware admission control (HTTP 429's gRPC
                    # sibling): RESOURCE_EXHAUSTED + a retry-after hint
                    # in the trailing metadata
                    try:
                        shed = await router.admission_check()
                    except Exception:
                        shed = None
                    from ray_tpu.serve.proxy import note_admission

                    retry_after = note_admission(f"grpc:{dep}", shed)
                    if shed is not None:
                        context.set_trailing_metadata((
                            ("retry-after", str(retry_after)),))
                        await context.abort(
                            grpc.StatusCode.RESOURCE_EXHAUSTED,
                            f"deployment over capacity "
                            f"({shed['reason']}); retry after "
                            f"{retry_after}s")
                    req = Request("GRPC", handler_call_details.method, {},
                                  metadata, request_bytes, body)
                    model_id = metadata.get("serve_multiplexed_model_id")
                    # root span per RPC, honoring a W3C traceparent riding
                    # the invocation metadata (same contract as HTTP)
                    tp = metadata.get("traceparent")
                    t0 = time.perf_counter()
                    code = "OK"
                    try:
                        with tracing.request_span(
                                "grpc.request",
                                {"traceparent": tp} if tp else None,
                                attributes={"ray_tpu.op": "serve_request",
                                            "rpc.method":
                                                handler_call_details.method,
                                            "rpc.app": dep}):
                            # compiled ingress rides here too (the router
                            # is shared with the HTTP proxy); streaming
                            # bodies stay dynamic — replica-affine
                            stream = bool(isinstance(body, dict)
                                          and body.get("stream"))
                            result = await router.submit(
                                "__call__", (req,), {}, model_id=model_id,
                                prefix_key=prompt_prefix_key(body),
                                allow_compiled=not stream)
                    except Exception as e:  # surface detail like HTTP's 500
                        code = "INTERNAL"
                        await context.abort(grpc.StatusCode.INTERNAL, repr(e))
                    finally:
                        try:
                            _get_serve_metrics()["request_seconds"].observe(
                                time.perf_counter() - t0,
                                tags={"route": f"grpc:{dep}", "code": code})
                        except Exception:
                            pass
                    if isinstance(result, bytes):
                        return result
                    return json.dumps(result, default=str).encode()

                return grpc.unary_unary_rpc_method_handler(
                    call,
                    request_deserializer=None,
                    response_serializer=None)

        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((Handler(),))
        bound = self._server.add_insecure_port(f"127.0.0.1:{port}")
        await self._server.start()
        return bound

    async def stop(self):
        loop = asyncio.get_running_loop()
        for router in list(self._routers.values()):
            try:
                await asyncio.wait_for(
                    loop.run_in_executor(None, router.shutdown_chain), 20)
            except Exception:
                pass
        if self._server is not None:
            await self._server.stop(grace=1.0)


@ray_tpu.remote
class GrpcProxyActor:
    """Per-cluster gRPC ingress actor (HTTP proxy's sibling)."""

    def __init__(self, controller_handle):
        self._controller = controller_handle
        self._impl = None
        self._port = None

    async def start(self, port: int = 0) -> int:
        # max_concurrency>1: serialize concurrent start() calls or two
        # servers get created and one leaks
        if not hasattr(self, "_start_lock"):
            self._start_lock = asyncio.Lock()
        async with self._start_lock:
            if self._port is None:
                self._impl = _GrpcServer(self._controller)
                self._port = await self._impl.start(port)
        return self._port

    async def ready(self) -> Optional[int]:
        return self._port

    async def stop(self):
        if self._impl is not None:
            await self._impl.stop()


def start_grpc(port: int = 0) -> int:
    """Start (or get) the cluster's gRPC ingress; returns the bound port
    (reference: `serve.start(grpc_options=...)`)."""
    from ray_tpu.serve import api

    controller = api._get_or_create_controller()
    try:
        proxy = ray_tpu.get_actor("serve-grpc-proxy")
    except ValueError:
        proxy = GrpcProxyActor.options(
            name="serve-grpc-proxy", lifetime="detached",
            get_if_exists=True, max_concurrency=64).remote(controller)
    return ray_tpu.get(proxy.start.remote(port), timeout=60)
