"""Content-addressed weight plane: replica cold start as a P2P pull.

Growing a model fleet means replica cold start dominates scaling: every
new replica re-reads its full checkpoint from a central path
(`serve/llm.py` -> `gpt2.load_params`), so fleet growth is serialized on
one store's read bandwidth and scale-to-zero is unaffordable. This
module applies the PR 13 prefix-store pattern to WEIGHTS: a published
param tree becomes first-class content-addressed objects on the PR 7
data plane, and a cold replica streams them from its peers instead.

- **publish**: the first replica (or trainer/driver) that holds a param
  tree flattens it into one contiguous byte stream (leaf order =
  template traversal order, the `gpt2.save_params` keying), cuts the
  stream into fixed-size SEGMENT objects sealed as raw `bytes`
  (`ray_tpu.put`), and seals a small manifest blob carrying the stream
  layout (per-leaf shape/dtype/offset + segment table + arch sidecar +
  content hash, the `train/checkpoint.py` shard/window metadata shape).
  One fire-and-forget push binds `weights_id -> manifest oid` on the
  head; the binding rides the next cluster_view broadcast as a
  directory weights row (`core/object_directory.py`).
- **resolve**: a cold replica resolves `weights_id -> manifest` from its
  process-cached directory — residency-checked, ZERO head RPCs.
- **pull**: leaves are read through `WindowedReader`s whose loader does
  RANGE fetches — raw-bytes segments have their payload at a fixed
  frame offset, so rows [r0, r1) of a leaf map to exact byte windows
  served by the existing `fetch_chunk(meta, offset, length)` data-server
  verb (`core/object_transfer.py`). A puller grabs only the windows it
  needs; `reshard_streaming` pipelines loader reads against device_put
  so peak host bytes stay ~`max_in_flight * chunk_bytes` regardless of
  model size. Sources come from the gossiped directory (primary first,
  then PullManager replica caches), so pulls fail over across nodes;
  any miss degrades to a whole-segment `ray_tpu.get` (node PullManager
  path) and finally to the checkpoint-path read — correctness never
  depends on the store.
- **LoRA hot-swap**: adapter deltas publish as small padded blobs under
  `lora::<base>::<adapter>` bindings; `OpenAIServer._engine_for` pulls
  them P2P before falling back to the adapter npz on disk.

Multi-tenant: hit/miss/byte counters are tagged per tenant; cold-start
latency lands in the `replica_cold_start_seconds` histogram tagged by
source (p2p vs checkpoint), and the resolve/pull/reshard phases emit
tracing spans so a cold start is attributable in the chrome timeline.
"""

from __future__ import annotations

import hashlib
import io
import pickle
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

WEIGHTS_FORMAT = "ray_tpu.weights.v1"
ADAPTER_FORMAT = "ray_tpu.lora.v1"

# raw `bytes` objects serialize as [8B n_buffers][8B meta_len][meta]
# [8B buf_len][payload] (core/serialization.py), so the payload starts at
# a FIXED offset inside the sealed frame — which is what makes exact
# byte-range reads through fetch_chunk possible without a header fetch
def _payload_off() -> int:
    from ray_tpu.core import serialization

    return 16 + len(serialization._BYTES_META) + 8


def _min_blob_bytes() -> int:
    # objects below the inline threshold ride actor replies, never the
    # sealed-object plane: a directory binding for one could not serve a
    # P2P pull (see prefix_store's inline_skipped). Small blobs
    # (manifests, adapters) are padded past it; pickle ignores the tail.
    from ray_tpu.core.store import INLINE_THRESHOLD

    return int(INLINE_THRESHOLD) + 4096


def _flag_int(env: str, default: int) -> int:
    import os

    try:
        return int(os.environ.get(env, default))
    except (TypeError, ValueError):
        return default


# ------------------------------------------------------------------ metrics
_metrics = None


def _get_metrics():
    global _metrics
    if _metrics is None:
        from ray_tpu.util import metrics as m

        _metrics = {
            "hits": m.Counter(
                "weight_store_hits_total",
                "Weight-store resolutions that delivered a full param "
                "tree / adapter from the P2P plane", tag_keys=("tenant",)),
            "misses": m.Counter(
                "weight_store_misses_total",
                "Weight-store resolutions that fell back to the "
                "checkpoint-path read (no resident binding, or the "
                "stream failed mid-pull)", tag_keys=("tenant",)),
            "bytes": m.Counter(
                "weight_store_bytes_total",
                "Weight bytes fetched from the cluster weight store",
                tag_keys=("tenant",)),
            "cold_start": m.Histogram(
                "replica_cold_start_seconds",
                "Wall seconds a replica spent materializing its params, "
                "by source (p2p = streamed from peers, checkpoint = "
                "central-path read)",
                buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120),
                tag_keys=("source",)),
        }
    return _metrics


def observe_cold_start(seconds: float, source: str) -> None:
    """Record one replica cold start (engine init calls this for BOTH
    sources so the histogram compares them on /metrics)."""
    try:
        _get_metrics()["cold_start"].observe(float(seconds),
                                             tags={"source": source})
    except Exception:
        pass


def _client():
    """The process's ray client, or None outside an initialized runtime
    (standalone engines in unit tests): every store operation silently
    no-ops without a cluster."""
    try:
        from ray_tpu.core import api as core_api

        if not core_api.is_initialized():
            return None
        return core_api._global_client()
    except Exception:
        return None


def adapter_store_key(base_weights_id: str, adapter_id: str) -> str:
    """Directory binding key for a LoRA adapter delta: scoped to the BASE
    weights identity so same-named adapters of different bases never
    collide."""
    return f"lora::{base_weights_id}::{adapter_id}"


def _tree_flatten_keyed(tree) -> List[Tuple[str, Any]]:
    """(key, leaf) pairs in template traversal order with the
    `gpt2.save_params` "/"-joined keying — publish and restore flatten
    the SAME way, so leaves match by position and by name."""
    import jax

    out = []
    for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                       for k in kp)
        out.append((key, leaf))
    return out


class _StreamPacker:
    """Cuts an incoming byte stream into fixed-size segment objects.

    Segments are exactly `segment_bytes` except the last, which absorbs
    the remainder (and is merged backward if it would fall below the
    inline threshold — every published segment must be pullable)."""

    def __init__(self, segment_bytes: int):
        self.segment_bytes = int(segment_bytes)
        self._buf = bytearray()
        self._h = hashlib.blake2b(digest_size=16)
        self.segments: List[dict] = []   # {"ref", "off", "nbytes"}
        self.total = 0

    def feed(self, data) -> None:
        mv = memoryview(data).cast("B")
        self._h.update(mv)
        self._buf += mv
        self.total += mv.nbytes
        # cut only while a full segment PLUS an above-inline tail remain
        # buffered: the invariant keeps the final segment (cut in
        # `finish`) at or above the inline floor, so every published
        # segment is pullable
        while len(self._buf) >= self.segment_bytes + _min_blob_bytes():
            self._cut(self.segment_bytes)

    def _cut(self, n: int) -> None:
        import ray_tpu

        chunk = bytes(self._buf[:n])
        del self._buf[:n]
        off = sum(s["nbytes"] for s in self.segments)
        self.segments.append({"ref": ray_tpu.put(chunk), "off": off,
                              "nbytes": len(chunk)})

    def finish(self) -> str:
        if self._buf:
            self._cut(len(self._buf))
        return "blake2b:" + self._h.hexdigest()


class WeightStoreClient:
    """One process's facade over the cluster weight tier (thread-safe:
    engine init, the publish executor, and adapter swaps share it)."""

    def __init__(self, fetch_timeout_s: float = 60.0,
                 max_published: int = 8):
        self.fetch_timeout_s = float(fetch_timeout_s)
        self.max_published = int(max_published)
        self.segment_bytes = _flag_int("RAY_TPU_WEIGHT_SEGMENT_BYTES",
                                       4 << 20)
        self.stream_chunk_bytes = _flag_int(
            "RAY_TPU_WEIGHT_STREAM_CHUNK_BYTES", 1 << 20)
        self.stream_in_flight = _flag_int(
            "RAY_TPU_WEIGHT_STREAM_IN_FLIGHT", 2)
        # weights_id -> {"manifest", "manifest_ref", "segment_refs"}:
        # pinned publications (the refs keep the bytes alive); bounded
        # LRU with explicit withdraw on eviction, like prefix_store pins
        self._published: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()
        # lifetime counters (stats()/tests; tagged Counters feed /metrics)
        self.hits = 0
        self.misses = 0
        self.bytes_fetched = 0
        self.range_fetches = 0
        self.local_reads = 0
        self.whole_pulls = 0
        self.published = 0
        self.inline_skipped = 0
        self.reannounced = 0
        self.last_load_stats: dict = {}
        # head-restart resilience: re-push bindings for live pins on
        # reconnect (the prefix_store pattern)
        self._reconnect_cb = None
        self._ensure_reconnect_hook(_client())
        # pre-import the streaming machinery NOW (engine init time):
        # first-import cost belongs to process startup, not inside a
        # replica's timed cold-start load
        try:
            from ray_tpu.util import tracing  # noqa: F401
            from ray_tpu.util.collective import reshard  # noqa: F401
        except Exception:
            pass

    # ----------------------------------------------------------- plumbing
    def _ensure_reconnect_hook(self, client) -> None:
        if client is None or self._reconnect_cb is not None:
            return
        import weakref

        ref = weakref.WeakMethod(self.reannounce)

        def _on_reconnect(_ref=ref, _client=client):
            m = _ref()
            if m is None:
                try:
                    _client.remove_reconnect_callback(_on_reconnect)
                except Exception:
                    pass
                return
            m()

        try:
            client.add_reconnect_callback(_on_reconnect)
            self._reconnect_cb = _on_reconnect
        except Exception:
            self._reconnect_cb = None

    def _count_miss(self, tenant: str) -> None:
        with self._lock:
            self.misses += 1
        try:
            _get_metrics()["misses"].inc(tags={"tenant": tenant})
        except Exception:
            pass

    def _count_hit(self, tenant: str) -> None:
        with self._lock:
            self.hits += 1
        try:
            _get_metrics()["hits"].inc(tags={"tenant": tenant})
        except Exception:
            pass

    def _count_bytes(self, n: int, tenant: str) -> None:
        with self._lock:
            self.bytes_fetched += int(n)
        try:
            _get_metrics()["bytes"].inc(int(n), tags={"tenant": tenant})
        except Exception:
            pass

    # -------------------------------------------------------------- publish
    def _put_blob(self, value: dict):
        """Seal a small control blob (manifest / adapter) as raw bytes,
        padded past the inline threshold so it is pullable and enters the
        gossiped directory (pickle stops at STOP; padding is inert)."""
        import ray_tpu

        payload = pickle.dumps(value, protocol=4)
        pad = _min_blob_bytes() - len(payload)
        if pad > 0:
            payload += b"\x00" * pad
        return ray_tpu.put(payload)

    def _pin(self, weights_id: str, ent: dict, client) -> None:
        evicted: List[Tuple[str, bytes]] = []
        with self._lock:
            old = self._published.pop(weights_id, None)
            self._published[weights_id] = ent
            self.published += 1
            while len(self._published) > self.max_published:
                wid, oldent = self._published.popitem(last=False)
                evicted.append((wid, oldent["manifest_ref"].id.binary()))
        if old is not None:
            evicted.append((weights_id, old["manifest_ref"].id.binary()))
        for wid, oid in evicted:
            # dropping the refs releases the bytes through the refcount
            # plane; the explicit withdraw retires the binding promptly.
            # oid-scoped: the head keeps a binding another publisher has
            # since rebound to its own live manifest
            try:
                client.head_push("withdraw_weights", weights_id=wid,
                                 oid=oid)
            except Exception:
                pass

    def _announced_ok(self, ref, client) -> bool:
        """Sealed past the inline threshold? Inline blobs never enter the
        directory, so a binding for one could never serve a pull."""
        from ray_tpu.core.object_directory import PULLABLE_KINDS

        meta = client.local_metas.get(ref.id)
        if meta is None or meta.kind not in PULLABLE_KINDS:
            with self._lock:
                self.inline_skipped += 1
            return False
        return True

    def publish_stream(self, weights_id: str,
                       leaves: Iterator[Tuple[str, tuple, Any,
                                              Iterator[np.ndarray]]],
                       arch: Optional[dict] = None) -> Optional[dict]:
        """Publish a weight byte stream: `leaves` yields
        (key, global_shape, dtype, row-block iterator) in template order;
        blocks are consumed one at a time, so peak publisher memory is
        ~one segment + one block regardless of model size. Returns the
        manifest, or None when there is no cluster / the stream is too
        small to live on the object plane."""
        client = _client()
        if client is None:
            return None
        self._ensure_reconnect_hook(client)
        packer = _StreamPacker(self.segment_bytes)
        params_meta: Dict[str, dict] = {}
        for key, shape, dtype, blocks in leaves:
            dt = np.dtype(dtype)
            off = packer.total
            n = 0
            for block in blocks:
                block = np.ascontiguousarray(np.asarray(block, dtype=dt))
                packer.feed(block.view(np.uint8).reshape(-1))
                n += block.nbytes
            params_meta[key] = {"shape": tuple(int(s) for s in shape),
                                "dtype": dt.str, "off": off, "nbytes": n}
        if packer.total == 0:
            return None
        content = packer.finish()
        if packer.total < _min_blob_bytes():
            # sub-inline model: its lone segment rides actor replies, not
            # the plane — count and skip (prefix_store semantics)
            with self._lock:
                self.inline_skipped += 1
            return None
        if not all(self._announced_ok(s["ref"], client)
                   for s in packer.segments):
            return None
        manifest = {"format": WEIGHTS_FORMAT, "weights_id": weights_id,
                    "hash": content, "arch": dict(arch) if arch else None,
                    "segment_bytes": self.segment_bytes,
                    "params": params_meta,
                    "segments": [{"oid": s["ref"].id.binary(),
                                  "off": s["off"], "nbytes": s["nbytes"]}
                                 for s in packer.segments],
                    "total_bytes": packer.total}
        try:
            manifest_ref = self._put_blob(manifest)
            if not self._announced_ok(manifest_ref, client):
                return None
            client.head_push("announce_weights", weights_id=weights_id,
                             oid=manifest_ref.id.binary())
        except Exception:
            return None
        self._pin(weights_id, {"manifest": manifest,
                               "manifest_ref": manifest_ref,
                               "segment_refs": [s["ref"]
                                                for s in packer.segments]},
                  client)
        return manifest

    def publish_params(self, params, weights_id: str,
                       arch: Optional[dict] = None) -> Optional[dict]:
        """Publish an in-memory param tree (the replica that just paid
        the checkpoint-path read shares it with the rest of the fleet)."""
        pairs = _tree_flatten_keyed(params)

        def leaves():
            for key, leaf in pairs:
                a = np.asarray(leaf)
                yield key, a.shape, a.dtype, iter([a])

        return self.publish_stream(weights_id, leaves(), arch=arch)

    def publish_sharded(self, path: str,
                        weights_id: Optional[str] = None,
                        arch: Optional[dict] = None) -> Optional[dict]:
        """Publish a `train/checkpoint.save_sharded` checkpoint straight
        from its windowed readers: rows stream from the npz seek-reads
        into the segment packer, so a multi-GB sharded checkpoint
        publishes under a bounded host budget."""
        from ray_tpu.train.checkpoint import open_sharded

        readers, _manifest = open_sharded(path)

        def leaves():
            for key in sorted(readers):
                r = readers[key]
                shape, dt = tuple(r.shape), np.dtype(r.dtype)
                if not shape:
                    yield key, shape, dt, iter(
                        [np.asarray(r.read(()), dt)])
                    continue
                row_bytes = dt.itemsize * int(
                    np.prod(shape[1:], dtype=np.int64) or 1)
                step = max(1, self.segment_bytes // max(1, row_bytes))

                def blocks(r=r, shape=shape, step=step):
                    for r0 in range(0, shape[0], step):
                        r1 = min(r0 + step, shape[0])
                        yield r.read(((r0, r1),)
                                     + tuple((0, s) for s in shape[1:]))

                yield key, shape, dt, blocks()

        return self.publish_stream(weights_id or path, leaves(), arch=arch)

    def reannounce(self) -> int:
        """Re-push bindings for every pinned publication (fired by the
        client's reconnect hook after a head restart)."""
        client = _client()
        if client is None:
            return 0
        with self._lock:
            pins = [(wid, ent["manifest_ref"].id.binary())
                    for wid, ent in self._published.items()]
        n = 0
        for wid, oid in pins:
            try:
                client.head_push("announce_weights", weights_id=wid,
                                 oid=oid)
                n += 1
            except Exception:
                pass
        with self._lock:
            self.reannounced += n
        return n

    # -------------------------------------------------------------- resolve
    def resolve(self, weights_id: str) -> Optional[dict]:
        """weights_id -> manifest, zero head RPCs: this process's pins
        first (no gossip round trip for same-process publications), then
        the broadcast-fed directory binding (residency-checked) with the
        manifest blob pulled over the data plane."""
        with self._lock:
            ent = self._published.get(weights_id)
            if ent is not None:
                self._published.move_to_end(weights_id)
                return ent["manifest"]
        client = _client()
        if client is None:
            return None
        try:
            binding = client.object_dir.weights_binding(weights_id)
        except Exception:
            binding = None
        if binding is None:
            return None
        import ray_tpu
        from ray_tpu.core.ids import ObjectID
        from ray_tpu.core.object_ref import ObjectRef

        try:
            blob = ray_tpu.get(ObjectRef(ObjectID(binding["oid"])),
                               timeout=self.fetch_timeout_s)
            manifest = pickle.loads(blob)
        except Exception:
            return None
        if (not isinstance(manifest, dict)
                or manifest.get("format") != WEIGHTS_FORMAT):
            return None
        return manifest

    # ----------------------------------------------------------- range pull
    def _local_meta(self, client, oid):
        """A locally-readable meta for the segment, if any: publisher's
        own seal, this process's pulled LRU, the node daemon's
        PullManager cache — or any same-node copy advertised in the
        gossiped directory (shared-memory store: a segment sealed by a
        NEIGHBOR process on this node mmaps directly, no socket; a
        remote node's meta fails the probe and falls through to the
        ranged fetch)."""
        for m in (client.local_metas.get(oid),
                  client._pulled.get(oid),
                  client._daemon_pulled.get(oid)):
            if m is not None and client._probe_readable(m):
                return m
        try:
            m = client.object_dir.lookup_meta(oid)
        except Exception:
            m = None
        if m is not None and client._probe_readable(m):
            return m
        return None

    def _fetch_range(self, oid_bytes: bytes, offset: int, length: int,
                     tenant: str = "base") -> bytes:
        """Exact byte window [offset, offset+length) of a segment's
        PAYLOAD. Local zero-copy read when any resident copy exists;
        otherwise a ranged `fetch_chunk` against the directory's sources
        (primary, then PullManager replicas — multi-source failover);
        finally a whole-segment pull through the normal get() path. Any
        raise means the caller falls back to the checkpoint path."""
        import asyncio

        from ray_tpu.core import protocol
        from ray_tpu.core.ids import ObjectID

        client = _client()
        if client is None:
            raise RuntimeError("no ray_tpu runtime")
        oid = ObjectID(oid_bytes)
        pay = _payload_off()
        local = self._local_meta(client, oid)
        if local is not None:
            view, release = client.store.get_raw(local, pay + offset,
                                                 length)
            try:
                data = bytes(view)
            finally:
                if release is not None:
                    release()
            with self._lock:
                self.local_reads += 1
            self._count_bytes(length, tenant)
            return data
        meta = client.object_dir.lookup_meta(oid)
        if meta is None:
            meta = client.local_metas.get(oid)
        if meta is not None:
            timeout = self.fetch_timeout_s + length / (4 << 20)

            async def _go():
                last: Optional[BaseException] = None
                for addr in client._sources_from_view(meta):
                    key = (addr[0], addr[1])
                    try:
                        conn = client._data_conns.get(key)
                        if conn is None or conn.closed:
                            conn = await protocol.connect(
                                key[0], key[1], name=f"data-{key[1]}")
                            client._data_conns[key] = conn
                        return await asyncio.wait_for(
                            conn.request("fetch_chunk", meta=meta,
                                         offset=pay + offset,
                                         length=length),
                            timeout=timeout)
                    except (protocol.RpcError, OSError, FileNotFoundError,
                            asyncio.TimeoutError) as e:
                        last = e
                        continue
                raise last or FileNotFoundError(f"no sources for {oid}")

            try:
                fut = asyncio.run_coroutine_threadsafe(_go(), client.loop)
                data = bytes(fut.result(timeout=timeout + 5))
                with self._lock:
                    self.range_fetches += 1
                self._count_bytes(length, tenant)
                return data
            except Exception:
                pass  # ranged path lost every source: try a whole pull
        # last resort before the checkpoint fallback: pull the WHOLE
        # segment through get() (node PullManager: in-flight dedup,
        # replica failover, head cold-miss fallback, LRU cache — later
        # ranges of this segment then read locally)
        import ray_tpu
        from ray_tpu.core.object_ref import ObjectRef

        blob = ray_tpu.get(ObjectRef(oid), timeout=self.fetch_timeout_s)
        with self._lock:
            self.whole_pulls += 1
        self._count_bytes(length, tenant)
        return bytes(blob[offset:offset + length])

    def prefetch_segments(self, manifest: dict, tenant: str = "base",
                          max_parallel: int = 4) -> int:
        """Bulk-pull every non-resident segment through the node
        PullManager (parallel whole-object gets) so the subsequent
        windowed reads all hit the local zero-copy path. A FULL restore
        touches every byte anyway: one pipelined pull per segment beats
        a socket round trip per leaf window. Partial consumers (TP ranks
        pulling only their rows) skip this and range-fetch. Returns the
        number of segments pulled; failures are left for the ranged path
        to retry source-by-source."""
        import ray_tpu
        from ray_tpu.core.ids import ObjectID
        from ray_tpu.core.object_ref import ObjectRef

        client = _client()
        if client is None:
            return 0
        cold = [seg["oid"] for seg in manifest.get("segments", ())
                if self._local_meta(client, ObjectID(seg["oid"])) is None]
        if not cold:
            return 0
        from concurrent.futures import ThreadPoolExecutor

        def _pull(oid_bytes: bytes) -> int:
            try:
                ray_tpu.get(ObjectRef(ObjectID(oid_bytes)),
                            timeout=self.fetch_timeout_s)
                return 1
            except Exception:
                return 0   # ranged fetch will fail over per source
        if len(cold) == 1:
            pulled = _pull(cold[0])
        else:
            with ThreadPoolExecutor(
                    max_workers=min(max_parallel, len(cold)),
                    thread_name_prefix="weight-prefetch") as pool:
                pulled = sum(pool.map(_pull, cold))
        with self._lock:
            self.whole_pulls += pulled
        return pulled

    def _read_stream(self, manifest: dict, offset: int, length: int,
                     tenant: str) -> bytes:
        """Assemble stream bytes [offset, offset+length) from the
        overlapping segments."""
        out = []
        need0, need1 = int(offset), int(offset) + int(length)
        for seg in manifest["segments"]:
            s0 = int(seg["off"])
            s1 = s0 + int(seg["nbytes"])
            lo, hi = max(need0, s0), min(need1, s1)
            if lo >= hi:
                continue
            out.append(self._fetch_range(seg["oid"], lo - s0, hi - lo,
                                         tenant=tenant))
        data = b"".join(out)
        if len(data) != length:
            raise FileNotFoundError(
                f"weight stream window [{need0}, {need1}) short: got "
                f"{len(data)} of {length} bytes")
        return data

    # ---------------------------------------------------------------- open
    def open(self, weights_id: str, tenant: str = "base"
             ) -> Optional[Tuple[Dict[str, Any], dict]]:
        """`weights_id` -> ({leaf key: WindowedReader}, manifest), the
        `train/checkpoint.open_sharded` contract served off the P2P
        plane: each reader's loader does exact range fetches, so
        `reshard_streaming` (or any windowed consumer) pulls only the
        rows it needs. None when no resident binding exists."""
        from ray_tpu.util.collective.reshard import WindowedReader

        manifest = self.resolve(weights_id)
        if manifest is None:
            return None
        readers: Dict[str, Any] = {}
        for key, ent in manifest["params"].items():
            shape = tuple(ent["shape"])
            dt = np.dtype(ent["dtype"])
            if not shape:
                def loader(k, r0, r1, _ent=ent, _dt=dt):
                    data = self._read_stream(manifest, _ent["off"],
                                             _dt.itemsize, tenant)
                    return np.frombuffer(data, dtype=_dt)

                readers[key] = WindowedReader((), dt, [((), key)], loader)
                continue
            trailing = shape[1:]
            row_bytes = dt.itemsize * int(
                np.prod(trailing, dtype=np.int64) or 1)

            def loader(k, r0, r1, _ent=ent, _dt=dt, _shape=shape,
                       _row=row_bytes):
                data = self._read_stream(manifest,
                                         _ent["off"] + r0 * _row,
                                         (r1 - r0) * _row, tenant)
                return np.frombuffer(data, dtype=_dt).reshape(
                    (r1 - r0,) + _shape[1:])

            readers[key] = WindowedReader(
                shape, dt, [(tuple((0, s) for s in shape), key)], loader)
        return readers, manifest

    # ------------------------------------------------------------- adapters
    def publish_adapter(self, adapter_key: str,
                        adapter: dict) -> Optional[dict]:
        """Publish a LoRA adapter delta ({path: {A, B, alpha}}) as one
        padded blob bound under `adapter_key` — small enough that range
        fetch buys nothing, hot-swapped often enough that P2P residency
        buys a lot."""
        client = _client()
        if client is None:
            return None
        self._ensure_reconnect_hook(client)
        blob = {"format": ADAPTER_FORMAT, "adapter": {
            path: {k: (np.asarray(v) if k in ("A", "B") else v)
                   for k, v in spec.items()}
            for path, spec in adapter.items()}}
        try:
            ref = self._put_blob(blob)
            if not self._announced_ok(ref, client):
                return None
            client.head_push("announce_weights", weights_id=adapter_key,
                             oid=ref.id.binary())
        except Exception:
            return None
        manifest = {"format": ADAPTER_FORMAT, "weights_id": adapter_key}
        self._pin(adapter_key, {"manifest": manifest, "manifest_ref": ref,
                                "segment_refs": [], "adapter": adapter},
                  client)
        return manifest

    def fetch_adapter(self, adapter_key: str,
                      tenant: str = "base") -> Optional[dict]:
        """Pull an adapter delta from the store; None on any miss (the
        caller loads the adapter npz from disk instead)."""
        with self._lock:
            ent = self._published.get(adapter_key)
            if ent is not None and "adapter" in ent:
                self._published.move_to_end(adapter_key)
        if ent is not None and "adapter" in ent:
            self._count_hit(tenant)
            return ent["adapter"]
        client = _client()
        if client is None:
            return None
        try:
            binding = client.object_dir.weights_binding(adapter_key)
        except Exception:
            binding = None
        if binding is None:
            self._count_miss(tenant)
            return None
        import ray_tpu
        from ray_tpu.core.ids import ObjectID
        from ray_tpu.core.object_ref import ObjectRef

        try:
            blob = pickle.loads(ray_tpu.get(
                ObjectRef(ObjectID(binding["oid"])),
                timeout=self.fetch_timeout_s))
        except Exception:
            self._count_miss(tenant)
            return None
        if (not isinstance(blob, dict)
                or blob.get("format") != ADAPTER_FORMAT):
            self._count_miss(tenant)
            return None
        size = sum(int(np.asarray(v).nbytes)
                   for spec in blob["adapter"].values()
                   for k, v in spec.items() if k in ("A", "B"))
        self._count_hit(tenant)
        self._count_bytes(size, tenant)
        return blob["adapter"]

    # ------------------------------------------------------------ high level
    def load_params(self, weights_id: str, base_cfg=None,
                    sharding_of: Optional[Callable] = None,
                    tenant: str = "base"):
        """Materialize a full param tree from the store: resolve the
        manifest from the gossiped directory, stream every leaf through
        `reshard_streaming` (peak host ~= in_flight * chunk_bytes), and
        return `(params, cfg)` exactly like `gpt2.load_params`. None on
        ANY miss — the caller falls back to the checkpoint-path read.

        `sharding_of(key, template_leaf)` supplies the destination
        sharding per leaf (TP engines pass their NamedShardings so chunks
        stream STRAIGHT into device shards); default is the process's
        first device."""
        import dataclasses
        import time as _time

        import jax

        from ray_tpu.models import gpt2
        from ray_tpu.util import tracing as _tracing
        from ray_tpu.util.collective.reshard import (last_stream_stats,
                                                     reshard_streaming)

        t0 = _time.perf_counter()
        with _tracing.start_span(
                "weights_resolve",
                attributes={"ray_tpu.op": "weights_resolve",
                            "weights_id": str(weights_id)[:80]}):
            opened = self.open(weights_id, tenant=tenant)
        if opened is None:
            self._count_miss(tenant)
            return None
        readers, manifest = opened
        arch = manifest.get("arch")
        if arch:
            base = base_cfg or gpt2.GPT2Config()
            cfg = dataclasses.replace(base, **arch)
        elif base_cfg is not None:
            cfg = base_cfg
        else:
            self._count_miss(tenant)
            return None
        template = jax.eval_shape(
            lambda: gpt2.init_params(jax.random.key(0), cfg))
        pairs = _tree_flatten_keyed(template)
        for key, leaf in pairs:
            ent = manifest["params"].get(key)
            if ent is None or tuple(ent["shape"]) != tuple(leaf.shape):
                self._count_miss(tenant)
                return None   # arch drift: let the checkpoint path decide
        if sharding_of is None:
            dev = jax.devices()[0]
            default_sh = jax.sharding.SingleDeviceSharding(dev)
            sharding_of = lambda key, leaf: default_sh  # noqa: E731
        leaves = []
        peak = 0
        resolve_s = _time.perf_counter() - t0
        try:
            with _tracing.start_span(
                    "weights_pull",
                    attributes={"ray_tpu.op": "weights_pull",
                                "bytes": int(manifest["total_bytes"]),
                                "leaves": len(pairs)}):
                if _flag_int("RAY_TPU_WEIGHT_PREFETCH", 1):
                    # full restore: bulk-pull cold segments up front so
                    # every window below is a local zero-copy read
                    self.prefetch_segments(manifest, tenant=tenant)
                for key, leaf in pairs:
                    with _tracing.start_span(
                            "weights_reshard",
                            attributes={"ray_tpu.op": "weights_reshard",
                                        "leaf": key[:80]}):
                        arr = reshard_streaming(
                            readers[key], sharding_of(key, leaf),
                            chunk_bytes=self.stream_chunk_bytes,
                            max_in_flight=self.stream_in_flight,
                            out_dtype=leaf.dtype)
                    peak = max(peak, last_stream_stats.get(
                        "peak_host_bytes", 0))
                    leaves.append(arr)
        except Exception:
            self._count_miss(tenant)
            return None
        treedef = jax.tree_util.tree_structure(template)
        params = jax.tree_util.tree_unflatten(treedef, leaves)
        self._count_hit(tenant)
        self.last_load_stats = {
            "weights_id": weights_id, "leaves": len(pairs),
            "bytes": int(manifest["total_bytes"]),
            "peak_host_bytes": int(peak),
            "chunk_bytes": self.stream_chunk_bytes,
            "max_in_flight": self.stream_in_flight,
            "resolve_s": resolve_s,
            "seconds": _time.perf_counter() - t0}
        return params, cfg

    # ---------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            return {"published": self.published,
                    "pinned": len(self._published),
                    "inline_skipped": self.inline_skipped,
                    "reannounced": self.reannounced,
                    "store_hits": self.hits,
                    "store_misses": self.misses,
                    "store_bytes_fetched": self.bytes_fetched,
                    "range_fetches": self.range_fetches,
                    "local_reads": self.local_reads,
                    "whole_pulls": self.whole_pulls,
                    "last_load": dict(self.last_load_stats)}


# process-wide store, rebuilt when the runtime is (re)initialized so pins
# never outlive their cluster
_store: Optional[WeightStoreClient] = None
_store_client_id: Optional[int] = None
_store_lock = threading.Lock()


def get_store() -> Optional[WeightStoreClient]:
    """The process's weight-store client, or None outside an initialized
    runtime."""
    client = _client()
    if client is None:
        return None
    global _store, _store_client_id
    with _store_lock:
        if _store is None or _store_client_id != id(client):
            _store = WeightStoreClient()
            _store_client_id = id(client)
        return _store


def maybe_publish_params_async(params, weights_id: str,
                               arch: Optional[dict] = None) -> bool:
    """Background publish of a param tree UNLESS the cluster already
    holds a resident binding (the dedup check runs before paying the
    flatten/hash/put work). The replica that just paid the central
    checkpoint read shares it without blocking its own init; failures
    are silent — the next replica simply pays the path read too."""
    client = _client()
    store = get_store()
    if client is None or store is None:
        return False
    with store._lock:
        if weights_id in store._published:
            return False
    try:
        if client.object_dir.weights_binding(weights_id) is not None:
            return False       # another replica already published it
    except Exception:
        pass

    def _go():
        try:
            store.publish_params(params, weights_id, arch=arch)
        except Exception:
            pass

    threading.Thread(target=_go, daemon=True,
                     name="weight-store-publish").start()
    return True
