"""Model multiplexing (reference `python/ray/serve/multiplex.py` +
`_private/multiplex.py`): a replica lazily loads up to N models, LRU-evicts,
and reports its loaded set so routers prefer warm replicas."""

from __future__ import annotations

import asyncio
import contextvars
import functools
import inspect
from collections import OrderedDict
from typing import Any, Callable, List, Optional

_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "rtpu_multiplexed_model_id", default="")
_replica_reporter: contextvars.ContextVar[Optional[Callable]] = \
    contextvars.ContextVar("rtpu_replica_reporter", default=None)


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id the caller asked for."""
    return _model_id.get()


def _set_request_model_id(model_id: str):
    _model_id.set(model_id)


class _MultiplexWrapper:
    def __init__(self, fn: Callable, owner: Any,
                 max_num_models_per_replica: int):
        self._fn = fn
        self._owner = owner
        self._max = max_num_models_per_replica
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = asyncio.Lock()

    @property
    def loaded_model_ids(self) -> List[str]:
        return list(self._models)

    def _report(self):
        reporter = _replica_reporter.get()
        if reporter is not None:
            try:
                reporter(self.loaded_model_ids)
            except Exception:
                pass

    async def load_model(self, model_id: str) -> Any:
        async with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
            if len(self._models) >= self._max:
                self._models.popitem(last=False)  # LRU eviction
            args = (self._owner, model_id) if self._owner is not None \
                else (model_id,)
            if inspect.iscoroutinefunction(self._fn):
                model = await self._fn(*args)
            else:
                model = self._fn(*args)
            self._models[model_id] = model
            self._report()
            return model

    def load_model_sync(self, model_id: str) -> Any:
        if model_id in self._models:
            self._models.move_to_end(model_id)
            return self._models[model_id]
        if len(self._models) >= self._max:
            self._models.popitem(last=False)
        args = (self._owner, model_id) if self._owner is not None \
            else (model_id,)
        model = self._fn(*args)
        self._models[model_id] = model
        self._report()
        return model

    def __call__(self, model_id: str):
        if inspect.iscoroutinefunction(self._fn):
            return self.load_model(model_id)
        return self.load_model_sync(model_id)


def multiplexed(max_num_models_per_replica: int = 3):
    """Decorator for a model-loader method: `@serve.multiplexed(...)
    async def get_model(self, model_id): ...`"""

    def deco(fn):
        attr = f"_rtpu_multiplex_{fn.__name__}"

        @functools.wraps(fn)
        def bound(self_or_model_id, *rest):
            # instance method: first arg is the owner instance
            if rest or not isinstance(self_or_model_id, str):
                owner, model_id = self_or_model_id, rest[0]
                wrapper = getattr(owner, attr, None)
                if wrapper is None:
                    wrapper = _MultiplexWrapper(fn, owner,
                                                max_num_models_per_replica)
                    setattr(owner, attr, wrapper)
            else:
                model_id = self_or_model_id
                wrapper = getattr(bound, "_wrapper", None)
                if wrapper is None:
                    wrapper = _MultiplexWrapper(fn, None,
                                                max_num_models_per_replica)
                    bound._wrapper = wrapper
            return wrapper(model_id)

        bound._rtpu_is_multiplexed = True
        return bound

    return deco


def loaded_model_ids_of(instance: Any) -> List[str]:
    ids: List[str] = []
    for name in dir(instance):
        if name.startswith("_rtpu_multiplex_"):
            wrapper = getattr(instance, name)
            if isinstance(wrapper, _MultiplexWrapper):
                ids.extend(wrapper.loaded_model_ids)
    return ids
