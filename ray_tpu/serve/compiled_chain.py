"""Compiled serve replica chain: the serving plane's standing fast path.

The dynamic handle path (serve/handle.py) pays per-request work on every
hop: routing-table refresh, replica pick, actor-call RPC submission,
result resolution. At saturation that control-plane work is the p99.
This module compiles a replica CHAIN (proxy -> preprocess -> ... -> LLM
replica) ONCE into pre-negotiated channel edges (ray_tpu/dag): the
caller-side client writes the input ring and reads the output ring —
zero control-plane RPCs per warm request (interposer-verified in
tests/test_compiled_chain.py). Scheduling work happens only at
(re)compile time, exactly the SURVEY §3.7 Compiled Graphs contract.

Batched ring entries: one ring entry carries up to `batch_max` queued
requests, so the engine's continuous batching still applies across a
compiled entry (replica-side `ReplicaActor.handle_chain` hands the whole
entry to `batch_call` when the deployment callable exposes it). The
writer coalesces ADAPTIVELY: an idle chain ships a lone request
immediately (no fixed batching delay on the low-load path); once entries
are already in flight it waits a few ms to fill the next entry — the
admission shape continuous batching wants at saturation. At saturation
the ring depth (`max_inflight`) keeps entries pipelined across stages
while earlier entries still execute.

Lanes: a replica's exec loop processes ring entries one at a time, which
would serialize an LLM engine across entries. `lanes=k` compiles k
INDEPENDENT channel rings, and lanes are SPREAD round-robin across the
deployment's healthy replicas (lane i runs over replica i % m for each
stage) — load balancing without per-request routing, decided once at
compile time. Lanes that land on the same replica each occupy one
replica executor thread, so entries still execute concurrently inside a
replica and the engine's per-step join/evict batches across them — the
compiled analogue of the dynamic path's concurrent actor calls, still
with zero per-request RPCs. Replica membership changes (death,
autoscale) reassign lanes through the same fence + recompile machinery;
`maybe_rebalance` lets a routing-table watcher trigger that recompile
when replicas were ADDED (no death to observe).

Failure model ("compiled chain actor dies -> recompile"): the chain
records the cluster epoch + a local generation at compile time. A chain
replica dying (actor_state pubsub, a drained error marker, or a ring
read/write timeout) FENCES the generation: new submissions route to the
dynamic handle path immediately, in-flight ring entries are drained
where possible and failed over to the dynamic path otherwise, and a
background thread recompiles against the deployment's surviving/replaced
replicas under the new generation (the PR 6 generation machinery + PR 3
epoch fences). Requests never observe a 500 for infrastructure reasons.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, List, Optional

CHAIN_ERR = "__rtpu_chain_error__"


def infra_error(detail: str) -> dict:
    """Marker for an infrastructure failure: the chain client fails the
    item over to the dynamic handle path instead of surfacing an error."""
    return {CHAIN_ERR: detail, "infra": True}


def is_chain_error(value) -> bool:
    return isinstance(value, dict) and CHAIN_ERR in value


class TracedValue:
    """Envelope for a 1-in-N sampled request: the W3C carrier rides the
    ring entry next to the value, so each stage can parent its span to
    the submitter's (and re-wrap its output with its OWN context for the
    next stage) — the compiled path's submit→stage→stage span chain with
    zero extra RPCs. Stages that don't know about tracing would see the
    envelope, so `ReplicaActor.handle_chain` unwraps before the callable
    and re-wraps after."""

    __slots__ = ("carrier", "value")

    def __init__(self, carrier, value):
        self.carrier = carrier
        self.value = value

    def __reduce__(self):
        return (TracedValue, (self.carrier, self.value))


def unwrap_traced(value):
    """(carrier, inner_value) — carrier is None for plain values."""
    if isinstance(value, TracedValue):
        return value.carrier, value.value
    return None, value


class ChainResponse:
    """Future for one request submitted to the chain."""

    def __init__(self, value):
        self.request = value
        self._ev = threading.Event()
        self._value = None
        self._exc: Optional[BaseException] = None
        self._cbs: List = []
        self._cb_lock = threading.Lock()

    def _finish(self) -> None:
        with self._cb_lock:
            self._ev.set()
            cbs, self._cbs = self._cbs, []
        for cb in cbs:
            try:
                cb(self)
            except Exception:
                pass

    def _set(self, value) -> None:
        self._value = value
        self._finish()

    def _set_exc(self, exc: BaseException) -> None:
        self._exc = exc
        self._finish()

    def done(self) -> bool:
        return self._ev.is_set()

    def add_done_callback(self, fn) -> None:
        """Invoke `fn(self)` once the response completes — immediately if
        it already has. Runs on the completing thread: an asyncio caller
        (the proxy) bridges with loop.call_soon_threadsafe instead of
        parking an executor thread per in-flight request."""
        with self._cb_lock:
            if not self._ev.is_set():
                self._cbs.append(fn)
                return
        try:
            fn(self)
        except Exception:
            pass

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("chain request timed out")
        if self._exc is not None:
            raise self._exc
        return self._value


class CompiledServeChain:
    """Compile a sequential deployment chain into channel edges.

    deployments: deployment names in chain order; each stage's output is
    the next stage's input (`value = stage(value)`).
    """

    def __init__(self, deployments: List[str], *,
                 lanes: int = 2, max_inflight: int = 4, batch_max: int = 8,
                 coalesce_ms: float = 3.0,
                 channel_capacity: int = 1 << 20,
                 entry_timeout_s: float = 60.0,
                 recompile_timeout_s: float = 60.0,
                 controller=None, plane: str = "serve_chain"):
        if not deployments:
            raise ValueError("need at least one deployment")
        self.deployments = list(deployments)
        # telemetry plane label: in-process chains publish as
        # "serve_chain"; the proxies' ingress chains publish as
        # "serve_proxy" so /api/hotpath and `ray-tpu top` attribute
        # stalls on the proxy edge separately
        self.plane = plane
        self.lanes = max(1, int(lanes))
        self.max_inflight = max(1, int(max_inflight))
        self.batch_max = max(1, int(batch_max))
        self.coalesce_s = max(0.0, coalesce_ms) / 1000.0
        self.capacity = channel_capacity
        self.entry_timeout_s = entry_timeout_s
        self.recompile_timeout_s = recompile_timeout_s
        self._controller = controller
        self._cdags: List[Any] = []
        self._targets: List[tuple] = []       # lane 0: (dep, tag, handle)
        self._lane_targets: List[List[tuple]] = []   # per lane
        self._compiled_tagsets: Dict[str, tuple] = {}
        self._last_rebalance = 0.0
        self._lane_rr = 0                     # round-robin cursor
        self._actor_ids: set = set()
        self.generation = 0
        self.epoch = None
        self._broken = True                   # until first compile
        self._shutdown = False
        self._lock = threading.RLock()
        self._subq: "queue.Queue" = queue.Queue()
        self._pendqs: List["queue.Queue"] = []   # one FIFO per lane
        self._lane_outstanding: List[int] = []
        self._dyn_handles: Dict[str, Any] = {}
        self._dyn_pool = None
        self._death_cb = None
        self._dead_aids: set = set()   # actor ids observed dead (pubsub)
        self._threads: List[threading.Thread] = []
        # lifetime counters (tests/bench/observability)
        self.stats = {"compiled": 0, "dynamic_fallback": 0, "recompiles": 0,
                      "fenced": 0, "entries": 0, "drained_on_fence": 0}
        # bounded event log (fences, recompile attempts, failovers):
        # the chain's own flight recorder for drills and debugging
        self.events: List[tuple] = []
        # hot-path observatory state: sampled-tracing counter, a small
        # completed-latency window (p99 for the hotpath row), and the
        # ring-telemetry thread started by start()
        self._trace_seq = 0
        self._lat_window: List[float] = []
        self.chain_key = "+".join(self.deployments)

    def _log(self, kind: str, **detail) -> None:
        with self._lock:
            self.events.append((round(time.time(), 3), kind, detail))
            if len(self.events) > 200:
                del self.events[:100]

    def _emit_chain_event(self, kind: str, **detail) -> None:
        """Mirror a chain lifecycle event into the head's flight-recorder
        lease-event log (state.list_lease_events / timeline reconcile
        row), so replica-death windows on the compiled plane show up
        next to the scheduler's view. Best-effort, and NEVER on the warm
        path — fences/failovers already pay control-plane RPCs."""
        try:
            from ray_tpu.core.api import _global_client

            _global_client().head_request(
                "chain_event", chain=self.chain_key, kind=kind,
                detail=detail)
        except Exception:
            pass

    # ----------------------------------------------------------- bring-up
    def _ctrl(self):
        if self._controller is None:
            from ray_tpu.serve.api import _get_or_create_controller

            self._controller = _get_or_create_controller()
        return self._controller

    def _resolve_targets(self, exclude: Optional[set] = None) -> List[tuple]:
        """ALL healthy replicas per deployment, from the controller's
        routing table (compile-time only — never on the request path).
        Returns [(deployment, {tag: handle}), ...] in chain order."""
        import ray_tpu

        targets = []
        deadline = time.monotonic() + self.recompile_timeout_s
        for dep in self.deployments:
            while True:
                table = ray_tpu.get(
                    self._ctrl().get_routing_table.remote(dep), timeout=30)
                if table is None:
                    raise KeyError(f"deployment {dep!r} not found")
                replicas = {t: h for t, h in table["replicas"].items()
                            if not exclude
                            or h._actor_id.binary() not in exclude}
                if replicas:
                    targets.append((dep, replicas))
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no live replicas for {dep!r} within "
                        f"{self.recompile_timeout_s}s")
                time.sleep(0.2)
        return targets

    def _compile(self, exclude: Optional[set] = None) -> None:
        """(Re)build the compiled chain; only path that talks to the
        control plane. Each lane is an independent channel ring, and lane
        i's stage-j ring targets replica i % m_j of deployment j — k
        lanes over m replicas spread the standing rings across the whole
        deployment (per-lane replica assignment, zero per-request
        routing). Lanes sharing a replica each occupy one of its executor
        threads, so their entries still execute concurrently."""
        from ray_tpu.core.api import _global_client
        from ray_tpu.dag.nodes import InputNode

        by_dep = self._resolve_targets(exclude=exclude)
        lane_targets = []
        for lane in range(self.lanes):
            picks = []
            for dep, replicas in by_dep:
                tags = sorted(replicas)
                tag = tags[lane % len(tags)]
                picks.append((dep, tag, replicas[tag]))
            lane_targets.append(picks)
        cdags = []
        for picks in lane_targets:
            with InputNode() as inp:
                node = inp
                for _dep, _tag, handle in picks:
                    node = handle.handle_chain.bind(node)
            cdags.append(node.experimental_compile(
                channel_capacity=self.capacity,
                max_inflight=self.max_inflight))
        # warm every lane BEFORE publishing the generation: the writer /
        # drainer threads must never share channel handles with these
        # warm-up reads (same cursor + scratch buffer), so the chain
        # stays broken (dynamic path) until the new rings proved alive
        try:
            refs = [cd.execute([], timeout=self.entry_timeout_s)
                    for cd in cdags]
            for ref in refs:
                ref.get(timeout=self.entry_timeout_s)
        except Exception:
            # a dead target mid-warm: release the half-built generation
            # (surviving stages' exec loops exit on channel close)
            for cd in cdags:
                try:
                    cd.teardown()
                except Exception:
                    pass
            raise
        with self._lock:
            self._targets = lane_targets[0]
            self._lane_targets = lane_targets
            self._compiled_tagsets = {
                dep: tuple(sorted(replicas)) for dep, replicas in by_dep}
            self._actor_ids = {h._actor_id.binary()
                               for picks in lane_targets
                               for _, _, h in picks}
            self._cdags = cdags
            self._pendqs = [queue.Queue() for _ in range(self.lanes)]
            self._lane_outstanding = [0] * self.lanes
            self._lane_rr = 0
            self.epoch = getattr(_global_client(), "cluster_epoch", None)
            self.generation += 1
            self._broken = False
            self.stats["recompiles"] += 1
        self._log("compiled", generation=self.generation,
                  targets=[[(d, t) for d, t, _h in picks]
                           for picks in lane_targets])

    def start(self) -> "CompiledServeChain":
        from ray_tpu.core.api import _global_client

        self._compile()   # compiles AND warms before going live

        # event-time death detection (PR 6 pattern): a chain actor dying
        # fences the generation immediately, not at the next timeout
        def on_actor_state(msg):
            if msg.get("state") not in ("DEAD", "RESTARTING"):
                return
            aid = msg.get("actor_id")
            with self._lock:
                hit = aid in self._actor_ids
                if hit:
                    self._dead_aids.add(aid)
                hit = hit and not self._broken
            if hit:
                # pubsub callbacks run on the client loop thread: fence
                # on a worker thread, never block the loop
                threading.Thread(
                    target=self._fence, args=("actor_death",),
                    daemon=True, name="chain-fence").start()

        self._death_cb = on_actor_state
        _global_client().subscribe_channel("actor_state", on_actor_state)

        t = threading.Thread(target=self._writer_loop, daemon=True,
                             name="chain-writer")
        t.start()
        self._threads.append(t)
        for lane in range(self.lanes):
            t = threading.Thread(target=self._drainer_loop, args=(lane,),
                                 daemon=True, name=f"chain-drainer-{lane}")
            t.start()
            self._threads.append(t)
        try:
            from ray_tpu.core import config as _cfg

            interval = float(_cfg.get("ring_telemetry_interval_s"))
        except Exception:
            interval = 0.0
        if interval > 0:
            t = threading.Thread(target=self._telemetry_loop,
                                 args=(interval,), daemon=True,
                                 name="chain-telemetry")
            t.start()
            self._threads.append(t)
        return self

    # ------------------------------------------------------------ request
    def _maybe_trace(self, value):
        """Sample 1-in-`tracing_compiled_sample_n` submissions for span
        capture when this request is traced (cluster tracing on, or the
        caller holds a span — e.g. an adopted client traceparent): opens
        the chain.submit span and wraps the value with its carrier so
        every stage span parents into the same trace. Unsampled requests
        pay one int check — the zero-RPC warm path is untouched."""
        try:
            from ray_tpu.core import config as _cfg
            from ray_tpu.util import tracing

            n = int(_cfg.get("tracing_compiled_sample_n"))
            if n <= 0 or not tracing.is_recording():
                return value
            seq = self._trace_seq
            self._trace_seq = seq + 1
            if seq % n:
                return value
            with tracing.start_span(
                    "chain.submit",
                    attributes={"ray_tpu.op": "chain_submit",
                                "chain": self.chain_key}) as sp:
                if sp is None:
                    return value
                carrier = {"traceparent": sp.traceparent()}
            return TracedValue(carrier, value)
        except Exception:
            return value

    def submit(self, value) -> ChainResponse:
        """Enqueue one request; never raises for infra reasons — a broken
        chain window routes to the dynamic handle path."""
        if self._shutdown:
            raise RuntimeError("chain was shut down")
        resp = ChainResponse(self._maybe_trace(value))
        resp._t0 = time.monotonic()
        with self._lock:
            broken = self._broken
        if broken:
            self._dynamic_submit([resp])
        else:
            self._subq.put(resp)
        return resp

    def call(self, value, timeout: Optional[float] = None):
        return self.submit(value).result(timeout or self.entry_timeout_s)

    __call__ = call

    # ------------------------------------------------------- worker loops
    def _writer_loop(self) -> None:
        """Adaptive batching dispatcher. An entry dispatches when a FREE
        lane exists AND (the entry is full, or the chain is idle, or the
        coalesce window expired). While every lane is busy, arriving
        requests keep joining the forming entry instead of queueing
        behind a busy ring — at saturation this is exactly the admission
        shape the engine's continuous batching wants, and an idle chain
        ships a lone request with zero added latency."""
        entries: List[ChainResponse] = []
        window_end = 0.0
        while not self._shutdown:
            if not entries:
                try:
                    entries = [self._subq.get(timeout=0.2)]
                except queue.Empty:
                    continue
                window_end = time.monotonic() + self.coalesce_s
            while len(entries) < self.batch_max:
                try:
                    entries.append(self._subq.get_nowait())
                except queue.Empty:
                    break
            with self._lock:
                broken, gen = self._broken, self.generation
                lane = None
                if not broken and self._cdags:
                    free = [i for i in range(len(self._cdags))
                            if self._lane_outstanding[i] < self.max_inflight]
                    if free:
                        # least-outstanding first, round-robin among ties:
                        # an idle chain would otherwise send EVERY entry
                        # down lane 0, defeating the multi-replica lane
                        # spread exactly when requests arrive sequentially
                        n_lanes = len(self._cdags)
                        rr = self._lane_rr
                        lane = min(free, key=lambda i: (
                            self._lane_outstanding[i], (i - rr) % n_lanes))
                        busy = any(o > 0 for o in self._lane_outstanding)
                        if (busy and len(entries) < self.batch_max
                                and time.monotonic() < window_end):
                            lane = None   # keep coalescing
                        else:
                            cdag = self._cdags[lane]
                            pendq = self._pendqs[lane]
                            self._lane_outstanding[lane] += 1
                            self._lane_rr = (lane + 1) % n_lanes
            if broken:
                self._dynamic_submit(entries)
                entries = []
                continue
            if lane is None:
                time.sleep(0.0005)
                continue
            try:
                ref = cdag.execute([e.request for e in entries],
                                   timeout=self.entry_timeout_s)
                self.stats["entries"] += 1
                pendq.put((gen, ref, entries))
                with self._lock:
                    fenced = gen != self.generation or self._broken
                if fenced:
                    # a fence swapped the pendqs while we were inside
                    # execute(): this put may have landed on an orphaned
                    # queue no drainer reads. Reclaim whatever is still
                    # there (the fence's own drain pops items exactly
                    # once too) so no caller is stranded.
                    self._reclaim_pendq(pendq)
            except Exception:
                # ring write failed (dead stage / torn down mid-swap):
                # fail over this batch, fence if still current
                self._lane_done(lane, gen)
                self._dynamic_submit(entries)
                self._maybe_fence(gen, "execute_failed")
            entries = []
        # shutdown: requests popped into the local coalescing buffer but
        # never dispatched still belong to callers — fail them over
        if entries:
            self._dynamic_submit(entries)

    def _reclaim_pendq(self, pendq: "queue.Queue") -> None:
        """Drain an orphaned (fenced-generation) pending queue: deliver
        what the rings still produced, fail over the rest."""
        while True:
            try:
                pgen, ref, entries = pendq.get_nowait()
            except queue.Empty:
                return
            try:
                results = ref.get(timeout=2.0)
                self._deliver(entries, results, pgen)
            except Exception:
                self._dynamic_submit([e for e in entries if not e.done()])

    def _drainer_loop(self, lane: int) -> None:
        while not self._shutdown:
            with self._lock:
                pendq = (self._pendqs[lane]
                         if lane < len(self._pendqs) else None)
            if pendq is None:
                time.sleep(0.2)
                continue
            try:
                gen, ref, entries = pendq.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                results = ref.get(timeout=self.entry_timeout_s)
            except Exception:
                self._lane_done(lane, gen)
                self._dynamic_submit(entries)
                self._maybe_fence(gen, "drain_failed")
                continue
            self._lane_done(lane, gen)
            self._deliver(entries, results, gen)

    def _lane_done(self, lane: int, gen: int) -> None:
        with self._lock:
            if gen == self.generation and lane < len(self._lane_outstanding):
                self._lane_outstanding[lane] -= 1

    def _telemetry_loop(self, interval: float) -> None:
        """Hot-path observatory sampler: lock-free shm-ring header
        snapshots per lane (occupancy + writer/reader stall attribution
        -> dag_ring_* gauges) plus one aggregated chain row (compiled
        p99 over the recent window, lifetime counters) — all riding the
        existing per-process metrics push. Zero new RPC channels, and
        the native snapshot never takes the channel mutex, so sampling a
        stalled ring cannot slow the stall down further."""
        from ray_tpu.dag.channel import publish_ring_stats
        from ray_tpu.util import metrics

        next_t = time.monotonic() + interval
        while not self._shutdown:
            time.sleep(0.1)
            if time.monotonic() < next_t:
                continue
            next_t = time.monotonic() + interval
            with self._lock:
                cdags = list(self._cdags)
                window = sorted(self._lat_window)
            snaps = {}
            for lane, cd in enumerate(cdags):
                try:
                    for name, s in cd.ring_snapshots().items():
                        snaps[f"{lane}/{name}"] = s
                except Exception:
                    pass
            if snaps:
                publish_ring_stats(self.plane, self.chain_key, snaps)
            try:
                row = {"generation": self.generation,
                       "compiled": self.stats["compiled"],
                       "dynamic_fallback": self.stats["dynamic_fallback"],
                       "fenced": self.stats["fenced"],
                       "entries": self.stats["entries"]}
                if window:
                    row["p99_s"] = round(
                        window[max(0, int(len(window) * 0.99) - 1)], 6)
                metrics.publish_workload(self.plane, self.chain_key, row)
            except Exception:
                pass

    def _deliver(self, entries, results, gen) -> None:
        ok = isinstance(results, list) and len(results) == len(entries)
        if not ok:
            self._dynamic_submit(entries)
            self._maybe_fence(gen, "bad_entry_shape")
            return
        infra_hit = False
        for e, r in zip(entries, results):
            # sampled requests come back in their trace envelope (the
            # last stage re-wrapped with its own context): unwrap before
            # the error check, deliver the inner value, and close the
            # trace with an end-to-end chain.deliver span backdated to
            # the submit time
            carrier, r = unwrap_traced(r)
            if is_chain_error(r):
                if r.get("infra"):
                    infra_hit = True
                    self._dynamic_submit([e])
                else:
                    e._set_exc(RuntimeError(r[CHAIN_ERR]))
            else:
                e._set(r)
                self.stats["compiled"] += 1
                dt = time.monotonic() - getattr(e, "_t0", time.monotonic())
                with self._lock:
                    self._lat_window.append(dt)
                    if len(self._lat_window) > 512:
                        del self._lat_window[:256]
                if carrier is not None:
                    try:
                        from ray_tpu.util import tracing

                        with tracing.start_span(
                                "chain.deliver", carrier=carrier,
                                attributes={"ray_tpu.op": "chain_deliver",
                                            "chain": self.chain_key,
                                            "duration_s": dt}) as sp:
                            if sp is not None:
                                sp.start_ts = time.time() - dt
                    except Exception:
                        pass
        if infra_hit:
            self._maybe_fence(gen, "infra_marker")

    # ------------------------------------------------------ failure plane
    def _maybe_fence(self, gen: int, reason: str) -> None:
        with self._lock:
            if gen != self.generation or self._broken:
                return
        self._fence(reason)

    def _fence(self, reason: str) -> None:
        """Fence the current generation: stop the compiled path, drain
        or fail over everything in flight, then recompile in background.
        Epoch semantics match PR 3: anything stamped with the old
        generation is rejected-and-reconciled, never silently retried."""
        with self._lock:
            if self._broken or self._shutdown:
                return
            self._broken = True
            self.stats["fenced"] += 1
            self.events.append((round(time.time(), 3), "fence",
                                {"reason": reason, "gen": self.generation}))
            cdags = self._cdags
            self._cdags = []
            pendqs = self._pendqs
            self._pendqs = []
            self._lane_outstanding = []
            gen = self.generation
        self._emit_chain_event("chain_fence", reason=reason, gen=gen)
        # drain-first: entries that already passed the dead stage may
        # still complete from the output ring; everything else fails
        # over. Bounded short — callers are waiting.
        failed_over = 0
        pending = []
        for pq in pendqs:
            while True:
                try:
                    pending.append(pq.get_nowait())
                except queue.Empty:
                    break
        for pgen, ref, entries in pending:
            # entries still in pendq were never delivered at all
            try:
                results = ref.get(timeout=2.0)
                self._deliver(entries, results, pgen)
                self.stats["drained_on_fence"] += len(entries)
            except Exception:
                undone = [e for e in entries if not e.done()]
                failed_over += len(undone)
                self._dynamic_submit(undone)
        # submissions queued but not yet written also fail over
        backlog = []
        while True:
            try:
                backlog.append(self._subq.get_nowait())
            except queue.Empty:
                break
        if backlog:
            failed_over += len(backlog)
            self._dynamic_submit(backlog)
        if failed_over:
            self._emit_chain_event("chain_failover", reason=reason,
                                   gen=gen, entries=failed_over)

        del gen   # fenced generation: superseded by the recompile below

        def _teardown_and_recompile():
            for cd in cdags:
                try:
                    cd.teardown()
                except Exception:
                    pass
            deadline = time.monotonic() + self.recompile_timeout_s
            while not self._shutdown and time.monotonic() < deadline:
                try:
                    # exclude pubsub-observed corpses: the controller may
                    # not have reconciled the death yet, and recompiling
                    # over one would fence again immediately
                    with self._lock:
                        dead = set(self._dead_aids)
                    self._compile(exclude=dead)   # warms before going live
                    return
                except Exception as e:  # noqa: BLE001
                    # stay broken: the dynamic path keeps serving while
                    # the controller replaces the replica; retry
                    self._log("recompile_retry", error=repr(e)[:200])
                    with self._lock:
                        stale = self._cdags
                        self._cdags = []
                        self._broken = True
                    for cd in stale:
                        try:
                            cd.teardown()
                        except Exception:
                            pass
                    time.sleep(0.5)

        threading.Thread(target=_teardown_and_recompile, daemon=True,
                         name="chain-recompile").start()

    def recompile(self) -> None:
        """Manual recompile (tests / membership change without a death)."""
        self._fence("manual")

    def maybe_rebalance(self, replica_tags: Dict[str, set],
                        min_interval_s: float = 5.0) -> bool:
        """Recompile when the deployment's healthy replica set GREW or
        otherwise drifted from what the lanes were compiled over (replica
        deaths already fence via pubsub; autoscale-up has no death to
        observe). Callers feed fresh routing-table tag sets — e.g. the
        proxy's 1 s table refresh — so this costs zero extra RPCs.
        Rate-limited: a fence drains in-flight entries to the dynamic
        path, so rebalance storms would hurt more than a briefly
        lopsided lane assignment. Returns True when a fence was issued."""
        with self._lock:
            if self._broken or self._shutdown or not self._compiled_tagsets:
                return False
            now = time.monotonic()
            if now - self._last_rebalance < min_interval_s:
                return False
            drift = False
            for dep, compiled in self._compiled_tagsets.items():
                fresh = replica_tags.get(dep)
                if fresh is not None and tuple(sorted(fresh)) != compiled:
                    drift = True
                    break
            if not drift:
                return False
            self._last_rebalance = now
        self._fence("rebalance")
        return True

    # ------------------------------------------------------- dynamic path
    def _dyn_handle(self, dep: str):
        if dep not in self._dyn_handles:
            from ray_tpu.serve.handle import DeploymentHandle

            self._dyn_handles[dep] = DeploymentHandle(dep, self._ctrl())
        return self._dyn_handles[dep]

    def _dynamic_submit(self, entries: List[ChainResponse]) -> None:
        """Serve entries through the dynamic handle path (router-level
        replica failover; never a 500 for infra reasons)."""
        if not entries:
            return
        if self._dyn_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            with self._lock:
                if self._dyn_pool is None:
                    self._dyn_pool = ThreadPoolExecutor(
                        max_workers=16, thread_name_prefix="chain-dyn")

        from ray_tpu.core.exceptions import (ActorDiedError,
                                             ActorUnavailableError,
                                             WorkerCrashedError)

        infra_excs = (ActorDiedError, ActorUnavailableError,
                      WorkerCrashedError, ConnectionError)

        entries = [e for e in entries if not e.done()]
        if not entries:
            return

        def run(e: ChainResponse):
            # infra-aware retry: right after a replica death the routing
            # table may still list the corpse until the controller
            # reconciles — refresh and retry until the replacement lands
            # (the never-500 contract), bounded by the entry timeout
            deadline = time.monotonic() + self.entry_timeout_s
            while True:
                try:
                    # a sampled request failing over sheds its trace
                    # envelope: the dynamic path opens its own spans
                    _carrier, value = unwrap_traced(e.request)
                    for dep in self.deployments:
                        h = self._dyn_handle(dep)
                        value = h.remote(value).result(
                            timeout=max(1.0, deadline - time.monotonic()))
                    e._set(value)
                    self.stats["dynamic_fallback"] += 1
                    return
                except infra_excs as exc:
                    if time.monotonic() > deadline:
                        e._set_exc(exc)
                        return
                    for dep in self.deployments:
                        try:
                            self._dyn_handle(dep)._refresh_table(force=True)
                        except Exception:
                            pass
                    time.sleep(0.2)
                except Exception as exc:
                    e._set_exc(exc)
                    return

        for e in entries:
            self._dyn_pool.submit(run, e)

    # ------------------------------------------------------------ control
    def targets(self) -> List[tuple]:
        """Lane 0's (deployment, tag) chain — kept for compatibility;
        lane_targets() exposes the full per-lane spread."""
        with self._lock:
            return [(d, t) for d, t, _h in self._targets]

    def lane_targets(self) -> List[List[tuple]]:
        with self._lock:
            return [[(d, t) for d, t, _h in picks]
                    for picks in self._lane_targets]

    def is_compiled(self) -> bool:
        with self._lock:
            return not self._broken

    def wait_compiled(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.is_compiled():
                return True
            time.sleep(0.1)
        return False

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        if self._death_cb is not None:
            try:
                from ray_tpu.core.api import _global_client

                _global_client().unsubscribe_channel("actor_state",
                                                     self._death_cb)
            except Exception:
                pass
        with self._lock:
            cdags = self._cdags
            self._cdags = []
            pendqs = self._pendqs
            self._pendqs = []
            self._broken = True
        # fail over anything still queued/in flight before teardown
        leftovers: List[ChainResponse] = []
        while True:
            try:
                leftovers.append(self._subq.get_nowait())
            except queue.Empty:
                break
        pend = []
        for pq in pendqs:
            while True:
                try:
                    pend.append(pq.get_nowait())
                except queue.Empty:
                    break
        for _gen, ref, entries in pend:
            try:
                results = ref.get(timeout=5.0)
                self._deliver(entries, results, _gen)
            except Exception:
                leftovers.extend(e for e in entries if not e.done())
        if leftovers:
            self._dynamic_submit(leftovers)
        for cd in cdags:
            try:
                cd.teardown()
            except Exception:
                pass
        # the writer may have been blocked inside execute() and a drainer
        # inside ref.get(); teardown woke them, and their exit/failover
        # paths submit through the dynamic pool — join them ALL before
        # closing the pool so no caller's entry is stranded by a
        # submit-after-shutdown
        for t in self._threads:
            t.join(timeout=15)
        if self._dyn_pool is not None:
            self._dyn_pool.shutdown(wait=True)
