"""Declarative app deployment from config files.

Parity: `serve deploy config.yaml` + `python/ray/serve/schema.py` /
`build_app.py` — a YAML/dict schema describing applications resolves import
paths, applies deployment overrides, and runs them. CLI: `ray-tpu serve
deploy <config.yaml>` / `ray-tpu serve status` / `ray-tpu serve shutdown`.

Schema (reference-shaped subset):

```yaml
applications:
  - name: app1
    route_prefix: /app1
    import_path: mypkg.mymodule:app       # Deployment or builder()
    args: {key: value}                    # passed to a builder callable
    deployments:                          # per-deployment overrides
      - name: Greeter
        num_replicas: 3
        max_ongoing_requests: 16
        compiled: true                    # proxies serve this deployment
        chain_config: {lanes: 4}          # over CompiledServeChain rings
```

Overrides map straight onto `Deployment.options(**opts)`, so every
dataclass field works — including `compiled`/`chain_config`, which flip
the deployment onto the proxies' compiled ingress (ring channels, lanes
spread across replicas; see serve/compiled_chain.py).
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional


def _resolve_import(path: str):
    mod_name, _, attr = path.partition(":")
    if not attr:
        raise ValueError(f"import_path {path!r} must be 'module:attr'")
    mod = importlib.import_module(mod_name)
    target = mod
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def build_app(app_cfg: Dict[str, Any]):
    """Resolve one application entry to a bound Deployment."""
    from ray_tpu.serve.api import Deployment

    target = _resolve_import(app_cfg["import_path"])
    if isinstance(target, Deployment):
        app = target
    elif callable(target):
        app = target(**(app_cfg.get("args") or {}))
    else:
        raise TypeError(f"{app_cfg['import_path']} resolved to {type(target)}; "
                        "expected a Deployment or a builder callable")
    for override in app_cfg.get("deployments") or []:
        oname = override.get("name")
        if oname not in (None, app.name):
            raise ValueError(
                f"deployment override names {oname!r} but the application's "
                f"deployment is {app.name!r}")
        opts = {k: v for k, v in override.items() if k != "name"}
        app = app.options(**opts)
    return app


def deploy_config(config: Dict[str, Any]) -> List[str]:
    """Deploy every application in a parsed config; returns app names."""
    from ray_tpu.serve import api

    if not isinstance(config, dict) or "applications" not in config:
        raise ValueError("config must be a mapping with an 'applications' "
                         "list (got empty or malformed config)")
    deployed = []
    for app_cfg in config.get("applications", []):
        app = build_app(app_cfg)
        api.run(app, name=app_cfg.get("name"),
                route_prefix=app_cfg.get("route_prefix"))
        deployed.append(app_cfg.get("name") or app.name)
    return deployed


def deploy_config_file(path: str) -> List[str]:
    try:
        import yaml
    except ImportError as e:
        raise ImportError(
            "deploying from YAML needs pyyaml (pip install pyyaml); "
            "alternatively call deploy_config() with a parsed dict") from e

    import os
    import sys

    with open(path) as f:
        config = yaml.safe_load(f)
    # apps typically live next to their config; make them importable the
    # way the reference CLI does
    cfg_dir = os.path.dirname(os.path.abspath(path))
    for p in (cfg_dir, os.getcwd()):
        if p not in sys.path:
            sys.path.insert(0, p)
    return deploy_config(config)
