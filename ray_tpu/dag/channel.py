"""Python wrapper over the native mutable shm channel (channel.cc).

Single-writer / N-reader single-slot handoff; values are serialized with the
core serializer. This is the data plane of compiled DAGs (reference:
`python/ray/experimental/channel/shared_memory_channel.py`).
"""

from __future__ import annotations

import ctypes
import os
from typing import Any, Optional

from ray_tpu.core import serialization
from ray_tpu.core.native_store import _build_and_load


class ChannelError(Exception):
    pass


class ChannelClosedError(ChannelError):
    pass


def _lib():
    lib = _build_and_load()
    if lib is None:
        raise ChannelError("native channel library unavailable")
    if not hasattr(lib.rtpu_chan_create, "_configured"):
        lib.rtpu_chan_create.restype = ctypes.c_void_p
        lib.rtpu_chan_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                         ctypes.c_uint32]
        lib.rtpu_chan_attach.restype = ctypes.c_void_p
        lib.rtpu_chan_attach.argtypes = [ctypes.c_char_p]
        lib.rtpu_chan_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rtpu_chan_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint64, ctypes.c_int64]
        lib.rtpu_chan_read.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64]
        lib.rtpu_chan_capacity.restype = ctypes.c_uint64
        lib.rtpu_chan_capacity.argtypes = [ctypes.c_void_p]
        lib.rtpu_chan_create._configured = True
    return lib


class Channel:
    """A named single-slot channel. Writers block until all readers consumed
    the previous value; readers block until a new value arrives."""

    def __init__(self, name: Optional[str] = None, capacity: int = 4 << 20,
                 num_readers: int = 1, _create: bool = True):
        self.name = name or f"rtpu_chan_{os.urandom(6).hex()}"
        self.capacity = capacity
        self.num_readers = num_readers
        self._last_seq = 0
        lib = _lib()
        if _create:
            self._h = lib.rtpu_chan_create(self.name.encode(), capacity,
                                           num_readers)
            self._owner = True
        else:
            self._h = lib.rtpu_chan_attach(self.name.encode())
            self._owner = False
        if not self._h:
            raise ChannelError(f"cannot open channel {self.name}")
        self._lib_ref = lib

    @classmethod
    def attach(cls, name: str) -> "Channel":
        ch = cls.__new__(cls)
        ch.name = name
        ch._last_seq = 0
        lib = _lib()
        ch._h = lib.rtpu_chan_attach(name.encode())
        if not ch._h:
            raise ChannelError(f"cannot attach channel {name}")
        ch._owner = False
        ch._lib_ref = lib
        ch.capacity = lib.rtpu_chan_capacity(ch._h)
        ch.num_readers = 0  # unknown on attach; only the header knows
        return ch

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        data = serialization.dumps(value)
        rc = self._lib_ref.rtpu_chan_write(
            self._h, data, len(data),
            -1 if timeout is None else int(timeout * 1000))
        if rc == -2:
            raise ChannelClosedError(self.name)
        if rc == -3:
            raise TimeoutError(f"write to {self.name} timed out")
        if rc == -4:
            raise ChannelError(
                f"value of {len(data)} bytes exceeds channel capacity "
                f"{self.capacity}")
        if rc != 0:
            raise ChannelError(f"write failed rc={rc}")

    def read(self, timeout: Optional[float] = None) -> Any:
        # reuse one capacity-sized buffer: create_string_buffer zero-fills,
        # which would dominate per-read cost for multi-MB channels
        buf = getattr(self, "_read_buf", None)
        if buf is None:
            cap = self._lib_ref.rtpu_chan_capacity(self._h)
            buf = self._read_buf = ctypes.create_string_buffer(cap)
        cap = len(buf)
        seq = ctypes.c_uint64()
        ln = ctypes.c_uint64()
        rc = self._lib_ref.rtpu_chan_read(
            self._h, self._last_seq, buf, cap, ctypes.byref(seq),
            ctypes.byref(ln), -1 if timeout is None else int(timeout * 1000))
        if rc == -2:
            raise ChannelClosedError(self.name)
        if rc == -3:
            raise TimeoutError(f"read from {self.name} timed out")
        if rc != 0:
            raise ChannelError(f"read failed rc={rc}")
        self._last_seq = seq.value
        # string_at copies exactly len bytes (buf.raw would copy the whole
        # capacity-sized buffer first)
        return serialization.loads(ctypes.string_at(buf, ln.value))

    def read_raw(self, last_seq: int, timeout: Optional[float] = None
                 ) -> tuple:
        """Stateless read: block for a value newer than `last_seq`, return
        (seq, serialized bytes). The per-reader cursor lives with the
        CALLER — this is what lets one attached channel serve any number
        of remote readers through the dag_chan_read RPC (reference
        remote-reader mutable objects,
        `core_worker/experimental_mutable_object_provider.cc`)."""
        buf = getattr(self, "_read_buf", None)
        if buf is None:
            cap = self._lib_ref.rtpu_chan_capacity(self._h)
            buf = self._read_buf = ctypes.create_string_buffer(cap)
        seq = ctypes.c_uint64()
        ln = ctypes.c_uint64()
        rc = self._lib_ref.rtpu_chan_read(
            self._h, last_seq, buf, len(buf), ctypes.byref(seq),
            ctypes.byref(ln), -1 if timeout is None else int(timeout * 1000))
        if rc == -2:
            raise ChannelClosedError(self.name)
        if rc == -3:
            raise TimeoutError(f"read from {self.name} timed out")
        if rc != 0:
            raise ChannelError(f"read failed rc={rc}")
        return seq.value, ctypes.string_at(buf, ln.value)

    def close(self, unlink: bool = False) -> None:
        if self._h:
            self._lib_ref.rtpu_chan_close(self._h, 1 if unlink else 0)
            self._h = None

    def __reduce__(self):
        # channels travel by name; receivers attach
        return (Channel.attach, (self.name,))


class RemoteChannelReader:
    """Read end of a channel hosted in ANOTHER node's process, over the
    host process's direct server (`dag_chan_read`). Cross-node compiled
    DAGs use these for every edge that spans nodes — the TPU payoff is
    host-side PP stage pipelining across slices over DCN (SURVEY §3.7).

    Per-reader state (the seq cursor) lives here; the serving side holds
    one shared attachment, so N remote readers cost one channel."""

    def __init__(self, name: str, addr):
        self.name = name
        self.addr = (addr[0], int(addr[1]))
        self._last_seq = 0

    def read(self, timeout: Optional[float] = None) -> Any:
        import time as _time

        from ray_tpu.core.api import _global_client

        client = _global_client()
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            # bounded per-RPC wait keeps the serving side's reader threads
            # from being parked indefinitely by an idle consumer
            wait = 1.0
            if deadline is not None:
                wait = min(wait, deadline - _time.monotonic())
                if wait <= 0:
                    raise TimeoutError(f"read from {self.name} timed out")
            reply = client.direct_request(
                self.addr, "dag_chan_read", name=self.name,
                last_seq=self._last_seq, max_wait=wait)
            if reply.get("closed"):
                raise ChannelClosedError(self.name)
            if reply.get("data") is None:
                continue   # server-side wait elapsed; retry until deadline
            self._last_seq = reply["seq"]
            return serialization.loads(reply["data"])

    def close(self, unlink: bool = False) -> None:
        pass   # the hosting process owns the channel's lifetime
