"""Python wrapper over the native mutable shm channel (channel.cc).

Single-writer / N-reader handoff over an N-slot ring; values are
serialized with the core serializer. This is the data plane of compiled
DAGs (reference: `python/ray/experimental/channel/shared_memory_channel.py`).

``num_slots=1`` is the classic single-slot mutable object (writer blocks
until every reader consumed the previous value). ``num_slots=k`` turns the
slot into a ring: the writer runs up to k values ahead of the slowest
reader cursor before blocking, which is what lets a compiled DAG keep
`max_inflight` iterations pipelined across stages.
"""

from __future__ import annotations

import ctypes
import os
import threading
import time
from typing import Any, Optional

from ray_tpu.core import serialization
from ray_tpu.core.native_store import _build_and_load


class ChannelError(Exception):
    pass


class ChannelClosedError(ChannelError):
    pass


def _lib():
    lib = _build_and_load()
    if lib is None:
        raise ChannelError("native channel library unavailable")
    if not hasattr(lib.rtpu_chan_create, "_configured"):
        lib.rtpu_chan_create.restype = ctypes.c_void_p
        lib.rtpu_chan_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                         ctypes.c_uint32, ctypes.c_uint32]
        lib.rtpu_chan_attach.restype = ctypes.c_void_p
        lib.rtpu_chan_attach.argtypes = [ctypes.c_char_p]
        lib.rtpu_chan_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rtpu_chan_shutdown.argtypes = [ctypes.c_void_p]
        lib.rtpu_chan_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint64, ctypes.c_int64]
        lib.rtpu_chan_read.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64]
        lib.rtpu_chan_reserve.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p)]
        lib.rtpu_chan_commit.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_chan_read_view.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int64]
        lib.rtpu_chan_ack.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_chan_capacity.restype = ctypes.c_uint64
        lib.rtpu_chan_capacity.argtypes = [ctypes.c_void_p]
        lib.rtpu_chan_num_readers.restype = ctypes.c_uint32
        lib.rtpu_chan_num_readers.argtypes = [ctypes.c_void_p]
        lib.rtpu_chan_num_slots.restype = ctypes.c_uint32
        lib.rtpu_chan_num_slots.argtypes = [ctypes.c_void_p]
        lib.rtpu_chan_stats.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_uint64)]
        lib.rtpu_chan_create._configured = True
    return lib


# ------------------------------------------------------------------ metrics
# dag_channel_wait_seconds: time spent BLOCKED on channel handoffs (writer
# waiting for a free ring slot / reader waiting for the next value) — the
# compiled hot path's analogue of rpc_latency_seconds. Paired with
# dag_channel_ops_total so wait-RATIO math has an unbiased denominator:
# the histogram's _count alone undercounts because read_raw (the remote-
# reader serving path) historically skipped it, and any wait-ratio
# computed against a biased op count overstates stall share. Lazily
# created so plain channel users outside a runtime never touch the
# metrics registry.
_wait_hist = None
_ops_counter = None
_wait_enabled = None


def _observe_wait(op: str, dt: float) -> None:
    global _wait_hist, _ops_counter, _wait_enabled
    if _wait_enabled is None:
        try:
            from ray_tpu.core import config as _config

            _wait_enabled = bool(_config.get("rpc_metrics"))
        except Exception:
            _wait_enabled = False
    if not _wait_enabled:
        return
    if _wait_hist is None:
        try:
            from ray_tpu.util import metrics

            _wait_hist = metrics.Histogram(
                "dag_channel_wait_seconds",
                "Time blocked on compiled-DAG channel handoffs",
                boundaries=[1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01,
                            0.05, 0.1, 0.5, 1.0, 5.0],
                tag_keys=("op",))
            _ops_counter = metrics.Counter(
                "dag_channel_ops_total",
                "Completed channel ops (every op, including the zero-wait "
                "fast path) — the denominator for wait-ratio math over "
                "dag_channel_wait_seconds",
                tag_keys=("op",))
        except Exception:
            _wait_enabled = False
            return
    _wait_hist.observe(dt, tags={"op": op})
    _ops_counter.inc(1.0, tags={"op": op})


class Channel:
    """A named ring channel. Writers block when the ring is full across
    all reader cursors; readers block until their next value arrives."""

    def __init__(self, name: Optional[str] = None, capacity: int = 4 << 20,
                 num_readers: int = 1, num_slots: int = 1,
                 _create: bool = True):
        self.name = name or f"rtpu_chan_{os.urandom(6).hex()}"
        self.capacity = capacity
        self.num_readers = num_readers
        self.num_slots = max(1, int(num_slots))
        self._last_seq = 0
        self._oplock = threading.Lock()
        self._close_lock = threading.Lock()
        lib = _lib()
        if _create:
            self._h = lib.rtpu_chan_create(self.name.encode(), capacity,
                                           num_readers, self.num_slots)
            self._owner = True
        else:
            self._h = lib.rtpu_chan_attach(self.name.encode())
            self._owner = False
        if not self._h:
            raise ChannelError(f"cannot open channel {self.name}")
        self._lib_ref = lib

    @classmethod
    def attach(cls, name: str) -> "Channel":
        ch = cls.__new__(cls)
        ch.name = name
        ch._last_seq = 0
        ch._oplock = threading.Lock()
        ch._close_lock = threading.Lock()
        lib = _lib()
        ch._h = lib.rtpu_chan_attach(name.encode())
        if not ch._h:
            raise ChannelError(f"cannot attach channel {name}")
        ch._owner = False
        ch._lib_ref = lib
        # the shm header is the source of truth: an attached handle keeps
        # the creator's reader count and ring depth, so re-serializing it
        # (__reduce__ -> attach) loses nothing and capacity checks stay
        # honest
        ch.capacity = lib.rtpu_chan_capacity(ch._h)
        ch.num_readers = lib.rtpu_chan_num_readers(ch._h)
        ch.num_slots = lib.rtpu_chan_num_slots(ch._h)
        return ch

    @staticmethod
    def _wrap(ptr: int, n: int) -> memoryview:
        # writable view over n bytes of the mapped slot (no copy)
        return memoryview((ctypes.c_char * n).from_address(ptr)).cast("B")

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        # zero-copy path: reserve the next ring slot and serialize INTO it
        # (SerializedObject.write_into), instead of flattening to a bytes
        # staging buffer and memcpy'ing that into the slot — one copy per
        # hop instead of two
        sobj = serialization.serialize(value)
        n = sobj.frame_bytes
        if n > self.capacity:
            raise ChannelError(
                f"value of {n} bytes exceeds channel capacity "
                f"{self.capacity}")
        ptr = ctypes.c_void_p()
        t0 = time.perf_counter()
        # _oplock serializes native ops on THIS handle so close() can
        # never munmap the segment under a thread still inside the
        # native call; shutdown() (lock-free) wakes a blocked op first
        with self._oplock:
            if not self._h:
                raise ChannelClosedError(self.name)
            rc = self._lib_ref.rtpu_chan_reserve(
                self._h, n,
                -1 if timeout is None else int(timeout * 1000),
                ctypes.byref(ptr))
            if rc == 0:
                # single-writer contract: the reserved slot is invisible
                # to readers until commit publishes the seq bump, so
                # serializing in place here cannot race a reader
                sobj.write_into(self._wrap(ptr.value, n))
                rc = self._lib_ref.rtpu_chan_commit(self._h, n)
        _observe_wait("write", time.perf_counter() - t0)
        if rc == -2:
            raise ChannelClosedError(self.name)
        if rc == -3:
            raise TimeoutError(f"write to {self.name} timed out")
        if rc == -4:
            raise ChannelError(
                f"value of {n} bytes exceeds channel capacity "
                f"{self.capacity}")
        if rc != 0:
            raise ChannelError(f"write failed rc={rc}")

    def _read_view(self, last_seq: int, timeout: Optional[float], op: str):
        """Shared view-read core: block for the value after `last_seq`,
        return (seq, len, ptr) WITHOUT copying or acking. Caller must ack
        via rtpu_chan_ack once done with the slot bytes."""
        seq = ctypes.c_uint64()
        ln = ctypes.c_uint64()
        ptr = ctypes.c_void_p()
        t0 = time.perf_counter()
        with self._oplock:
            if not self._h:
                raise ChannelClosedError(self.name)
            rc = self._lib_ref.rtpu_chan_read_view(
                self._h, last_seq, ctypes.byref(seq), ctypes.byref(ln),
                ctypes.byref(ptr),
                -1 if timeout is None else int(timeout * 1000))
        _observe_wait(op, time.perf_counter() - t0)
        if rc == -2:
            raise ChannelClosedError(self.name)
        if rc == -3:
            raise TimeoutError(f"read from {self.name} timed out")
        if rc != 0:
            raise ChannelError(f"read failed rc={rc}")
        return seq.value, ln.value, ptr.value

    def _ack(self, seq: int) -> None:
        # quick non-blocking native call; _close_lock (never held across a
        # blocking op) keeps close() from munmapping under it
        with self._close_lock:
            if self._h:
                self._lib_ref.rtpu_chan_ack(self._h, seq)

    def read(self, timeout: Optional[float] = None) -> Any:
        """Consume the next value. Zero-copy slot path: deserialize
        straight from a view over the ring slot, then ack — no staging
        buffer memcpy, no string_at copy. Meta-only frames (dicts etc.)
        deserialize with zero buffer copies; out-of-band buffers (numpy)
        pay exactly one owning copy (the result must outlive the slot)."""
        seq, ln, ptr = self._read_view(self._last_seq, timeout, "read")
        try:
            value = serialization.loads_view(self._wrap(ptr, ln))
        finally:
            self._ack(seq)
        self._last_seq = seq
        return value

    def read_zc(self, timeout: Optional[float] = None) -> "SlotView":
        """Consume the next value as a PINNED zero-copy view: the ring
        slot is not acked (so the writer cannot reclaim it) until the
        returned SlotView is released. `value()` deserializes fully
        aliasing the slot — out-of-band numpy buffers point INTO shm with
        no copy at all. The caller must release() (or use the context
        manager) exactly once; a leaked view wedges the writer when the
        ring wraps back to that slot."""
        seq, ln, ptr = self._read_view(self._last_seq, timeout, "read")
        self._last_seq = seq
        return SlotView(self, seq, self._wrap(ptr, ln))

    def read_raw(self, last_seq: int, timeout: Optional[float] = None
                 ) -> tuple:
        """Stateless read: block for the value after `last_seq`, return
        (seq, serialized bytes). The per-reader cursor lives with the
        CALLER — this is what lets one attached channel serve any number
        of remote readers through the dag_chan_read RPC (reference
        remote-reader mutable objects,
        `core_worker/experimental_mutable_object_provider.cc`)."""
        seq, ln, ptr = self._read_view(last_seq, timeout, "raw_read")
        try:
            # one owning copy (the bytes cross an RPC); the staging-buffer
            # path paid two (slot->buf memcpy + string_at)
            data = bytes(self._wrap(ptr, ln))
        finally:
            self._ack(seq)
        return seq, data

    def snapshot(self) -> dict:
        """Lock-free telemetry snapshot of the shm ring header: the native
        side reads the counters WITHOUT the channel mutex, so a monitoring
        thread can sample a channel whose writer or reader is currently
        stalled inside it. Stall attribution: `writer_stall_s` accrues
        while the writer blocks on a full ring (slow READER is the
        bottleneck); `reader_stall_s` accrues while a reader blocks on an
        empty ring (slow WRITER / upstream is the bottleneck)."""
        arr = (ctypes.c_uint64 * 8)()
        with self._close_lock:
            if not self._h:
                raise ChannelClosedError(self.name)
            self._lib_ref.rtpu_chan_stats(self._h, arr)
        return {
            "name": self.name,
            "seq": int(arr[0]),
            "occupancy": int(arr[1]),
            "num_slots": int(arr[2]),
            "writer_stall_s": arr[3] / 1e9,
            "reader_stall_s": arr[4] / 1e9,
            "writes": int(arr[5]),
            "reads": int(arr[6]),
            "closed": bool(arr[7]),
        }

    def shutdown(self) -> None:
        """Set the closed flag and wake blocked peers WITHOUT unmapping
        (close() would pull the mapping out from under a thread still
        blocked in read/write on this handle). Any attached handle may
        fence a channel this way — the teardown path for channels whose
        creator process died. `_close_lock` (never held across a
        blocking native call) guards the handle against a concurrent
        close() freeing it mid-use."""
        with self._close_lock:
            if self._h:
                self._lib_ref.rtpu_chan_shutdown(self._h)

    def close(self, unlink: bool = False) -> None:
        # shutdown first (under _close_lock only, which no blocking op
        # holds): wakes any op blocked inside the native call so it
        # releases _oplock; then munmap under BOTH locks — close can
        # never pull the mapping out from under a concurrent
        # read/write, and a concurrent close()/shutdown() can never
        # touch the freed handle (lock order: _oplock then _close_lock)
        self.shutdown()
        with self._oplock:
            with self._close_lock:
                if self._h:
                    self._lib_ref.rtpu_chan_close(self._h,
                                                  1 if unlink else 0)
                    self._h = None

    def __reduce__(self):
        # channels travel by name; receivers attach (and recover the true
        # num_readers / num_slots from the shm header)
        return (Channel.attach, (self.name,))


class SlotView:
    """One ring value viewed in place (``Channel.read_zc``): ``view()``
    and ``value()`` alias the shm slot directly; the slot stays pinned —
    the writer blocks before overwriting it — until ``release()``. The
    PR-7 DLPack-adoption discipline applied to ring slots: consume the
    bytes where they already are, give the slot back explicitly."""

    __slots__ = ("_chan", "seq", "_mv", "_released")

    def __init__(self, chan: "Channel", seq: int, mv: memoryview):
        self._chan = chan
        self.seq = seq
        self._mv = mv
        self._released = False

    def view(self) -> memoryview:
        if self._released:
            raise ChannelError(f"slot view {self.seq} already released")
        return self._mv

    def value(self) -> Any:
        """Deserialize fully aliasing the slot: out-of-band buffers
        (numpy arrays) point INTO shm — zero copies. The result is only
        valid until release(); consumers that outlive the slot must copy
        what they keep (or use Channel.read, which owns its result)."""
        return serialization.deserialize(
            serialization.SerializedObject.from_view(self.view()))

    def release(self) -> None:
        """Ack the slot (idempotent), letting the writer reclaim it."""
        if not self._released:
            self._released = True
            self._mv = None
            self._chan._ack(self.seq)

    def __enter__(self) -> "SlotView":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class RemoteChannelReader:
    """Read end of a channel hosted in ANOTHER node's process, over the
    host process's direct server (`dag_chan_read`). Cross-node compiled
    DAGs use these for every edge that spans nodes — the TPU payoff is
    host-side PP stage pipelining across slices over DCN (SURVEY §3.7).

    Per-reader state (the seq cursor) lives here; the serving side holds
    one shared attachment, so N remote readers cost one channel."""

    def __init__(self, name: str, addr):
        self.name = name
        self.addr = (addr[0], int(addr[1]))
        self._last_seq = 0

    def read(self, timeout: Optional[float] = None) -> Any:
        import time as _time

        from ray_tpu.core.api import _global_client

        client = _global_client()
        deadline = None if timeout is None else _time.monotonic() + timeout
        t0 = _time.perf_counter()
        while True:
            # bounded per-RPC wait keeps the serving side's reader threads
            # from being parked indefinitely by an idle consumer
            wait = 1.0
            if deadline is not None:
                wait = min(wait, deadline - _time.monotonic())
                if wait <= 0:
                    raise TimeoutError(f"read from {self.name} timed out")
            reply = client.direct_request(
                self.addr, "dag_chan_read", name=self.name,
                last_seq=self._last_seq, max_wait=wait)
            if reply.get("closed"):
                raise ChannelClosedError(self.name)
            if reply.get("data") is None:
                continue   # server-side wait elapsed; retry until deadline
            self._last_seq = reply["seq"]
            _observe_wait("remote_read", _time.perf_counter() - t0)
            return serialization.loads(reply["data"])

    def close(self, unlink: bool = False) -> None:
        pass   # the hosting process owns the channel's lifetime


# ----------------------------------------------------------- ring telemetry
# Per-lane ring series, published on the EXISTING per-process metrics push
# (gauges -> /metrics, one workload row per plane -> the head's hotpath
# aggregation). Zero new RPC channels: this is host-side sampling of the
# shm header the hot path already maintains.
_ring_gauges = None


def publish_ring_stats(plane: str, key: str, snaps: dict) -> None:
    """Publish ring telemetry for one compiled plane (a serve chain or a
    pipeline stage set). `snaps` maps lane label -> Channel.snapshot()
    dict. Gauges carry per-lane series; the aggregated workload row
    (kind "hotpath") carries the plane totals the watchdog and
    /api/hotpath consume. Best-effort: telemetry must never take down
    the plane it watches."""
    global _ring_gauges
    try:
        from ray_tpu.util import metrics

        if _ring_gauges is None:
            tags = ("plane", "key", "lane")
            _ring_gauges = {
                "occ": metrics.Gauge(
                    "dag_ring_occupancy",
                    "Live values in the shm ring (written, not yet acked "
                    "by every reader), per lane", tag_keys=tags),
                "stall": metrics.Gauge(
                    "dag_ring_stall_seconds",
                    "Cumulative blocked time on the ring by side: "
                    "side=writer means the ring was full (slow reader), "
                    "side=reader means it was empty (slow writer)",
                    tag_keys=tags + ("side",)),
            }
        occ = wstall = rstall = writes = reads = 0.0
        depth = 0
        for lane, s in snaps.items():
            t = {"plane": plane, "key": key, "lane": str(lane)}
            _ring_gauges["occ"].set(float(s["occupancy"]), tags=t)
            _ring_gauges["stall"].set(
                s["writer_stall_s"], tags={**t, "side": "writer"})
            _ring_gauges["stall"].set(
                s["reader_stall_s"], tags={**t, "side": "reader"})
            occ += s["occupancy"]
            wstall += s["writer_stall_s"]
            rstall += s["reader_stall_s"]
            writes += s["writes"]
            reads += s["reads"]
            depth = max(depth, s["num_slots"])
        metrics.publish_workload("hotpath", f"{plane}:{key}", {
            "plane": plane,
            "lanes": len(snaps),
            "depth": depth,
            "occupancy": occ,
            "writer_stall_s": round(wstall, 6),
            "reader_stall_s": round(rstall, 6),
            "writes": writes,
            "reads": reads,
        })
    except Exception:
        pass
