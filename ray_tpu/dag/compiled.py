"""Compiled DAG execution: per-actor loops over native shm channels,
cross-node via remote-reader RPC channels.

Lowering (reference `python/ray/dag/compiled_dag_node.py:809` CompiledDAG +
`do_exec_tasks` :191): every ClassMethodNode becomes a READ→COMPUTE→WRITE
step in a long-running loop pushed to its actor; edges become single-slot
mutable shm channels (ray_tpu/_native/channel.cc) living in the WRITER's
process. The driver writes input channels and blocks on output channels —
per-iteration cost is condvar handoffs, bypassing the task RPC path
entirely (SURVEY §3.7: µs-scale channel reads vs ~ms task overhead).

Cross-node edges (reference remote-reader mutable objects,
`experimental/channel/shared_memory_channel.py` +
`core_worker/experimental_mutable_object_provider.cc`): a consumer on a
different node gets a `RemoteChannelReader` that reads through the writer
process's direct server (`dag_chan_read`), so a compiled pipeline can span
nodes — host-side PP stage pipelining across TPU slices over DCN.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag.channel import (Channel, ChannelClosedError,
                                 RemoteChannelReader)
from ray_tpu.dag.nodes import (ClassMethodNode, DAGNode, InputNode,
                               MultiOutputNode)

# dag_step_seconds: end-to-end latency of one compiled iteration as the
# driver sees it (execute() write -> output ring read). Lazy: compiled
# DAGs can run outside an initialized metrics registry.
_step_hist = None


def _observe_step(dt: float) -> None:
    global _step_hist
    if _step_hist is None:
        try:
            from ray_tpu.util import metrics

            _step_hist = metrics.Histogram(
                "dag_step_seconds",
                "Compiled-DAG iteration latency (input write to output "
                "read at the driver)",
                boundaries=[1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1,
                            0.5, 1.0, 5.0, 30.0])
        except Exception:
            return
    try:
        _step_hist.observe(dt)
    except Exception:
        pass


class CompiledDAGRef:
    """Future for one compiled execution (reference compiled_dag_ref.py)."""

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx
        self._value = None
        self._done = False

    def get(self, timeout: Optional[float] = 30):
        # each drain fills the OLDEST pending iteration's refs; loop
        # until OURS is filled so out-of-order gets (natural with
        # max_inflight > 1) resolve correctly instead of returning an
        # unfilled placeholder. A timeout raises WITHOUT consuming or
        # poisoning anything — ring cursors stay aligned with _pending,
        # and a later get() simply resumes the drain.
        deadline = (None if timeout is None
                    else time.perf_counter() + timeout)
        while not self._done:
            left = None
            if deadline is not None:
                left = deadline - time.perf_counter()
                if left <= 0:
                    raise TimeoutError("compiled DAG output timed out")
            self._dag._drain_until(self._idx, left)
        if isinstance(self._value, Exception):
            raise self._value
        return self._value


class CompiledDAG:
    def __init__(self, output_node: DAGNode, channel_capacity: int = 4 << 20,
                 max_inflight: int = 2):
        self.capacity = channel_capacity
        # ring depth of every edge: how many iterations the driver may
        # keep in flight before execute() backpressures on the input
        # ring. 1 = classic single-slot serialization on the slowest
        # stage; >1 overlaps stages across iterations (the compiled
        # pipelining win)
        self.max_inflight = max(1, int(max_inflight))
        self.output_node = output_node
        order = output_node.topo_order()

        self.input_nodes: List[InputNode] = [
            n for n in order if isinstance(n, InputNode)]
        self.method_nodes: List[ClassMethodNode] = [
            n for n in order if isinstance(n, ClassMethodNode)]
        if isinstance(output_node, MultiOutputNode):
            self.leaf_nodes = list(output_node.outputs)
        else:
            self.leaf_nodes = [output_node]
        for n in order:
            if not isinstance(n, (InputNode, ClassMethodNode, MultiOutputNode)):
                raise TypeError(
                    f"compiled DAGs support actor-method pipelines; got {n!r}")
        for leaf in self.leaf_nodes:
            if not isinstance(leaf, ClassMethodNode):
                raise TypeError("compiled DAG outputs must be actor methods")

        # consumers per producing node: downstream method nodes + the driver
        self.consumers: Dict[str, int] = {n.uuid: 0 for n in order}
        for n in self.method_nodes:
            for up in n.upstream():
                self.consumers[up.uuid] += 1
        for leaf in self.leaf_nodes:
            self.consumers[leaf.uuid] += 1

        self.actors: Dict[Any, Any] = {}
        for n in self.method_nodes:
            self.actors[n.actor_handle._actor_id] = n.actor_handle

        # filled by _start (placement-dependent)
        self.chan_names: Dict[str, str] = {}     # producing uuid -> name
        self.input_channels: Dict[str, Channel] = {}
        self.leaf_readers: List[Any] = []
        self._remote_created: List[Tuple[Tuple[str, int], str]] = []
        import threading

        self._loop_refs = []
        self._started = False
        self._torn_down = False
        self._pending: List[List[CompiledDAGRef]] = []
        self._drain_lock = threading.Lock()
        self._teardown_lock = threading.Lock()

    # ------------------------------------------------------------ planning
    def _start(self) -> None:
        import os

        from ray_tpu.core.api import _global_client
        from ray_tpu.core.ids import NodeID

        client = _global_client()
        my_node = client.node_id.binary()
        my_addr = ("127.0.0.1", client.direct_port)

        # placement of every endpoint
        actor_node: Dict[Any, bytes] = {}
        actor_addr: Dict[Any, Tuple[str, int]] = {}
        for key in self.actors:
            reply = client.head_request("get_actor_address",
                                        actor_id=key.binary())
            if reply["state"] == "DEAD":
                raise RuntimeError(
                    f"cannot compile over dead actor: "
                    f"{reply.get('death_cause')}")
            actor_node[key] = reply.get("node_id") or my_node
            actor_addr[key] = tuple(reply["address"])

        producer_key: Dict[str, Any] = {}       # uuid -> actor key | None
        for n in self.method_nodes:
            producer_key[n.uuid] = n.actor_handle._actor_id

        def producer_node(uuid: str) -> bytes:
            key = producer_key.get(uuid)
            return my_node if key is None else actor_node[key]

        def producer_addr(uuid: str) -> Tuple[str, int]:
            key = producer_key.get(uuid)
            return my_addr if key is None else actor_addr[key]

        for n in self.input_nodes + self.method_nodes:
            if self.consumers[n.uuid]:
                self.chan_names[n.uuid] = f"rtpu_chan_{os.urandom(6).hex()}"

        def chan_ref(up: DAGNode, consumer_node: bytes):
            """How a consumer on `consumer_node` reads `up`'s output."""
            name = self.chan_names[up.uuid]
            if producer_node(up.uuid) == consumer_node:
                return ("chan", name)
            return ("rchan", (name, producer_addr(up.uuid)))

        # create every channel IN ITS WRITER'S PROCESS before any loop
        # starts (two-phase: no attach/create races)
        for node in self.input_nodes:
            if node.uuid not in self.chan_names:
                continue
            self.input_channels[node.uuid] = Channel(
                name=self.chan_names[node.uuid], capacity=self.capacity,
                num_readers=self.consumers[node.uuid],
                num_slots=self.max_inflight)
        for n in self.method_nodes:
            if n.uuid not in self.chan_names:
                continue
            key = producer_key[n.uuid]
            client.direct_request(
                actor_addr[key], "dag_chan_create",
                name=self.chan_names[n.uuid], capacity=self.capacity,
                num_readers=self.consumers[n.uuid],
                num_slots=self.max_inflight)
            self._remote_created.append(
                (actor_addr[key], self.chan_names[n.uuid]))

        # per-actor schedules, channel refs resolved against placement
        self.actor_schedules: Dict[Any, List[dict]] = {}
        for n in self.method_nodes:
            key = n.actor_handle._actor_id
            node_of_actor = actor_node[key]
            arg_sources = []
            for a in n.args:
                if isinstance(a, DAGNode):
                    arg_sources.append(chan_ref(a, node_of_actor))
                else:
                    arg_sources.append(("const", a))
            kwarg_sources = {}
            for k, v in n.kwargs.items():
                if isinstance(v, DAGNode):
                    kwarg_sources[k] = chan_ref(v, node_of_actor)
                else:
                    kwarg_sources[k] = ("const", v)
            self.actor_schedules.setdefault(key, []).append({
                "method": n.method,
                "args": arg_sources,
                "kwargs": kwarg_sources,
                "out_chan": self.chan_names.get(n.uuid),
                # device edges (reference torch_tensor_accelerator_channel):
                # a @method(tensor_transport="device") output stays in the
                # producer's device store; only a descriptor rides the shm
                # channel, consumers fetch via the device-object plane
                "transport": (n.actor_handle._methods.get(n.method)
                              or {}).get("tensor_transport"),
            })

        # driver-side readers for the outputs
        for leaf in self.leaf_nodes:
            kind, val = chan_ref(leaf, my_node)
            if kind == "chan":
                self.leaf_readers.append(Channel.attach(val))
            else:
                self.leaf_readers.append(RemoteChannelReader(*val))

        for key, schedule in self.actor_schedules.items():
            ref = client.call_actor(key, "__rtpu_dag_exec_loop__",
                                    (schedule,), {})
            self._loop_refs.append(ref)
        self._started = True

    # ------------------------------------------------------------- control
    def execute(self, *inputs, timeout: Optional[float] = None) -> Any:
        """Write inputs; returns CompiledDAGRef(s) for the output value(s).
        `timeout` bounds the input-ring write — with max_inflight
        iterations already in flight the write backpressures until a
        ring slot frees (or raises TimeoutError, e.g. a dead stage)."""
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        if not self._started:
            self._start()
        if len(inputs) < len(self.input_nodes):
            raise ValueError(
                f"need {len(self.input_nodes)} inputs, got {len(inputs)}")
        # `timeout` bounds only the FIRST ring write (the backpressure
        # point): once any input is written the iteration is committed,
        # and timing out a LATER input would leave the rings
        # desynchronized (input k holding one more value than input
        # k+1, silently mispairing every subsequent iteration). The
        # remaining writes block until their ring frees a slot, which
        # is guaranteed to happen as consumers drain earlier iterations.
        for n, node in enumerate(self.input_nodes):
            self.input_channels[node.uuid].write(
                inputs[node.index], timeout=timeout if n == 0 else None)
        refs = [CompiledDAGRef(self, i) for i in range(len(self.leaf_nodes))]
        t0 = time.perf_counter()
        for r in refs:
            r._t0 = t0
        self._pending.append(refs)
        return refs[0] if len(refs) == 1 else refs

    def _drain_until(self, idx: int, timeout: Optional[float]) -> None:
        """Read the oldest pending iteration's outputs into its ref set.

        Serialized (fence/teardown paths may race a drainer thread on
        the same DAG — unsynchronized interleaved leaf reads would pair
        iterations with the wrong refs), and RESUMABLE: a read timeout
        propagates without popping the set or advancing other leaves'
        work past it, so ring cursors and _pending stay aligned and the
        next drain continues where this one stopped. Only terminal
        channel closure poisons refs."""
        acquired = self._drain_lock.acquire(
            timeout=-1 if timeout is None else max(0.01, timeout))
        if not acquired:
            raise TimeoutError("compiled DAG output timed out")
        try:
            if not self._pending:
                raise RuntimeError("no execution in flight")
            from ray_tpu.dag.runtime import materialize_channel_value

            refs = self._pending[0]
            for i, reader in enumerate(self.leaf_readers):
                if refs[i]._done:
                    continue   # resumed drain: this leaf already read
                try:
                    refs[i]._value = materialize_channel_value(
                        reader.read(timeout=timeout))
                except ChannelClosedError as e:
                    refs[i]._value = e
                refs[i]._done = True
            self._pending.pop(0)
        finally:
            self._drain_lock.release()
        dt = time.perf_counter() - getattr(refs[0], "_t0", time.perf_counter())
        _observe_step(dt)
        from ray_tpu.util import tracing

        if tracing.is_recording():
            # one span per compiled iteration: start_span stamps start_ts
            # at entry, so backdate it to the execute() write
            with tracing.start_span(
                    "dag.step",
                    attributes={"ray_tpu.op": "dag_step",
                                "duration_s": dt}) as span:
                if span is not None:
                    span.start_ts = time.time() - dt

    def ring_snapshots(self) -> Dict[str, dict]:
        """Lock-free telemetry snapshots of every ring this driver holds a
        LOCAL handle to (input rings + same-node output rings), keyed by
        channel name. Remote-reader edges are skipped — their header
        lives in the writer's process and is sampled there. Feeds
        `publish_ring_stats` / the hot-path observatory; never blocks on
        a stalled ring."""
        out: Dict[str, dict] = {}
        if self._torn_down:
            return out
        for ch in list(self.input_channels.values()):
            try:
                out[ch.name] = ch.snapshot()
            except Exception:
                pass
        for reader in self.leaf_readers:
            if isinstance(reader, Channel):
                try:
                    out[reader.name] = reader.snapshot()
                except Exception:
                    pass
        return out

    def teardown(self, kill_actors: bool = False) -> None:
        # atomic check-then-set: the chain's shutdown and its recompile
        # thread may race here; a double native close is a use-after-free
        with self._teardown_lock:
            if self._torn_down:
                return
            self._torn_down = True
        # close() is shutdown-then-munmap-under-the-op-lock: it wakes a
        # writer blocked in execute() / a drainer blocked on an output
        # ring and only unmaps once they left the native call. Closing
        # the leaf readers also fences rings whose stage process DIED
        # (nobody else can set the closed flag), so blocked readers fail
        # over promptly instead of waiting out their full timeout.
        for ch in self.input_channels.values():
            ch.close(unlink=True)
        for reader in self.leaf_readers:
            if isinstance(reader, Channel):
                try:
                    reader.close()
                except Exception:
                    pass
        if self._started:
            from ray_tpu.core.api import _global_client

            client = _global_client()
            # close writer-hosted channels THROUGH the process-level RPC:
            # it runs on the worker's event loop, so it works even while
            # the exec loop occupies the actor executor
            for addr, name in self._remote_created:
                try:
                    client.direct_request(addr, "dag_chan_close",
                                          name=name, unlink=True)
                except Exception:
                    pass
        if kill_actors:
            import ray_tpu

            for handle in self.actors.values():
                try:
                    ray_tpu.kill(handle)
                except Exception:
                    pass
        elif self._started:
            import ray_tpu

            # loops exit via ChannelClosedError; join them
            for ref in self._loop_refs:
                try:
                    ray_tpu.get(ref, timeout=10)
                except Exception:
                    pass

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass
