"""Compiled DAG execution: per-actor loops over native shm channels.

Lowering (reference `python/ray/dag/compiled_dag_node.py:809` CompiledDAG +
`do_exec_tasks` :191): every ClassMethodNode becomes a READ→COMPUTE→WRITE
step in a long-running loop pushed to its actor; edges become single-slot
mutable shm channels (ray_tpu/_native/channel.cc). The driver writes input
channels and blocks on output channels — per-iteration cost is condvar
handoffs, bypassing the task RPC path entirely (SURVEY §3.7: µs-scale
channel reads vs ~ms task overhead).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.dag.channel import Channel, ChannelClosedError
from ray_tpu.dag.nodes import (ClassMethodNode, DAGNode, InputNode,
                               MultiOutputNode)


class CompiledDAGRef:
    """Future for one compiled execution (reference compiled_dag_ref.py)."""

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx
        self._value = None
        self._done = False

    def get(self, timeout: Optional[float] = 30):
        if not self._done:
            self._dag._drain_until(self._idx, timeout)
        if isinstance(self._value, Exception):
            raise self._value
        return self._value


class CompiledDAG:
    def __init__(self, output_node: DAGNode, channel_capacity: int = 4 << 20):
        self.capacity = channel_capacity
        self.output_node = output_node
        order = output_node.topo_order()

        self.input_nodes: List[InputNode] = [
            n for n in order if isinstance(n, InputNode)]
        self.method_nodes: List[ClassMethodNode] = [
            n for n in order if isinstance(n, ClassMethodNode)]
        if isinstance(output_node, MultiOutputNode):
            self.leaf_nodes = list(output_node.outputs)
        else:
            self.leaf_nodes = [output_node]
        for n in order:
            if not isinstance(n, (InputNode, ClassMethodNode, MultiOutputNode)):
                raise TypeError(
                    f"compiled DAGs support actor-method pipelines; got {n!r}")
        for leaf in self.leaf_nodes:
            if not isinstance(leaf, ClassMethodNode):
                raise TypeError("compiled DAG outputs must be actor methods")

        # consumers per producing node: downstream method nodes + the driver
        consumers: Dict[str, int] = {n.uuid: 0 for n in order}
        for n in self.method_nodes:
            for up in n.upstream():
                consumers[up.uuid] += 1
        for leaf in self.leaf_nodes:
            consumers[leaf.uuid] += 1

        # one channel per produced value (input node or method output)
        self.channels: Dict[str, Channel] = {}
        for n in self.input_nodes + self.method_nodes:
            if consumers[n.uuid] == 0:
                continue
            self.channels[n.uuid] = Channel(
                capacity=channel_capacity, num_readers=consumers[n.uuid])

        # group steps by actor, preserving topo order
        self.actor_schedules: Dict[Any, List[dict]] = {}
        self.actors: Dict[Any, Any] = {}
        for n in self.method_nodes:
            handle = n.actor_handle
            key = handle._actor_id
            self.actors[key] = handle
            arg_sources = []
            for a in n.args:
                if isinstance(a, DAGNode):
                    arg_sources.append(("chan", self.channels[a.uuid].name))
                else:
                    arg_sources.append(("const", a))
            kwarg_sources = {}
            for k, v in n.kwargs.items():
                if isinstance(v, DAGNode):
                    kwarg_sources[k] = ("chan", self.channels[v.uuid].name)
                else:
                    kwarg_sources[k] = ("const", v)
            out = self.channels.get(n.uuid)
            self.actor_schedules.setdefault(key, []).append({
                "method": n.method,
                "args": arg_sources,
                "kwargs": kwarg_sources,
                "out_chan": out.name if out else None,
            })

        self._loop_refs = []
        self._started = False
        self._torn_down = False
        self._pending: List[List[CompiledDAGRef]] = []

    # ------------------------------------------------------------- control
    def _start(self) -> None:
        from ray_tpu.core.api import _global_client

        client = _global_client()
        for key, schedule in self.actor_schedules.items():
            ref = client.call_actor(key, "__rtpu_dag_exec_loop__",
                                    (schedule,), {})
            self._loop_refs.append(ref)
        self._started = True

    def execute(self, *inputs) -> Any:
        """Write inputs; returns CompiledDAGRef(s) for the output value(s)."""
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        if not self._started:
            self._start()
        if len(inputs) < len(self.input_nodes):
            raise ValueError(
                f"need {len(self.input_nodes)} inputs, got {len(inputs)}")
        for node in self.input_nodes:
            self.channels[node.uuid].write(inputs[node.index])
        refs = [CompiledDAGRef(self, i) for i in range(len(self.leaf_nodes))]
        self._pending.append(refs)
        return refs[0] if len(refs) == 1 else refs

    def _drain_until(self, idx: int, timeout: Optional[float]) -> None:
        """Read one iteration's outputs into the oldest pending ref set."""
        if not self._pending:
            raise RuntimeError("no execution in flight")
        refs = self._pending.pop(0)
        for i, leaf in enumerate(self.leaf_nodes):
            ch = self.channels[leaf.uuid]
            try:
                refs[i]._value = ch.read(timeout=timeout)
            except (ChannelClosedError, TimeoutError) as e:
                refs[i]._value = e
            refs[i]._done = True

    def teardown(self, kill_actors: bool = False) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        for ch in self.channels.values():
            ch.close(unlink=True)
        if kill_actors:
            import ray_tpu

            for handle in self.actors.values():
                try:
                    ray_tpu.kill(handle)
                except Exception:
                    pass
        elif self._started:
            import ray_tpu

            # loops exit via ChannelClosedError; join them
            for ref in self._loop_refs:
                try:
                    ray_tpu.get(ref, timeout=10)
                except Exception:
                    pass

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass
