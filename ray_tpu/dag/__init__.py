"""Lazy DAGs over tasks/actors + compiled execution over shm channels.

Capability parity with the reference's `ray.dag` (`python/ray/dag/`,
SURVEY §3.7): `.bind()` builds the graph; `.execute()` runs it eagerly as
tasks/actor calls; `.experimental_compile()` lowers actor-method pipelines
to long-running per-actor loops connected by native mutable shm channels
(ray_tpu/_native/channel.cc), replacing per-call RPCs with condvar wakes.
"""

from ray_tpu.dag.channel import Channel, ChannelClosedError
from ray_tpu.dag.nodes import (ClassMethodNode, DAGNode, FunctionNode,
                               InputNode, MultiOutputNode)
from ray_tpu.dag.compiled import CompiledDAG

__all__ = [
    "Channel",
    "ChannelClosedError",
    "CompiledDAG",
    "DAGNode",
    "FunctionNode",
    "ClassMethodNode",
    "InputNode",
    "MultiOutputNode",
]
