"""DAG node types + .bind() surface (reference python/ray/dag/*_node.py)."""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    def __init__(self, args: Tuple = (), kwargs: Optional[dict] = None):
        self.uuid = uuid.uuid4().hex[:12]
        self.args = args
        self.kwargs = kwargs or {}

    # ---- traversal -------------------------------------------------------
    def upstream(self) -> List["DAGNode"]:
        ups = [a for a in self.args if isinstance(a, DAGNode)]
        ups += [v for v in self.kwargs.values() if isinstance(v, DAGNode)]
        return ups

    def topo_order(self) -> List["DAGNode"]:
        seen: Dict[str, DAGNode] = {}
        order: List[DAGNode] = []

        def visit(node: DAGNode):
            if node.uuid in seen:
                return
            seen[node.uuid] = node
            for up in node.upstream():
                visit(up)
            order.append(node)

        visit(self)
        return order

    # ---- eager execution -------------------------------------------------
    def execute(self, *input_values) -> Any:
        """Run the DAG as ordinary tasks/actor calls; returns ObjectRef(s)
        (eager path, reference dag_node.py execute)."""
        results: Dict[str, Any] = {}
        for node in self.topo_order():
            results[node.uuid] = node._run(results, input_values)
        return results[self.uuid]

    def _materialize(self, value, results):
        if isinstance(value, DAGNode):
            return results[value.uuid]
        return value

    def _run(self, results, input_values):
        raise NotImplementedError

    def experimental_compile(self, channel_capacity: int = 4 << 20,
                             max_inflight: int = 2):
        from ray_tpu.dag.compiled import CompiledDAG

        return CompiledDAG(self, channel_capacity=channel_capacity,
                           max_inflight=max_inflight)

    def __rshift__(self, other):  # small convenience for linear pipelines
        if callable(getattr(other, "bind", None)):
            return other.bind(self)
        raise TypeError(f"cannot chain into {other!r}")


class InputNode(DAGNode):
    """Placeholder for the runtime input; supports `with InputNode() as inp:`."""

    def __init__(self, index: int = 0):
        super().__init__()
        self.index = index

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _run(self, results, input_values):
        return input_values[self.index]


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self.remote_fn = remote_fn

    def _run(self, results, input_values):
        args = [self._materialize(a, results) for a in self.args]
        kwargs = {k: self._materialize(v, results)
                  for k, v in self.kwargs.items()}
        return self.remote_fn.remote(*args, **kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self.actor_handle = actor_handle
        self.method = method

    def _run(self, results, input_values):
        args = [self._materialize(a, results) for a in self.args]
        kwargs = {k: self._materialize(v, results)
                  for k, v in self.kwargs.items()}
        return getattr(self.actor_handle, self.method).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__(args=tuple(outputs))
        self.outputs = outputs

    def _run(self, results, input_values):
        return [results[o.uuid] for o in self.outputs]
