"""Actor-side compiled-DAG loop (reference do_exec_tasks,
compiled_dag_node.py:191): attach edge channels, then loop
READ -> COMPUTE -> WRITE until the driver closes the channels.

Edge refs come in two flavors (resolved by the driver's placement pass):
("chan", name)            — same-node: attach the shm channel directly
("rchan", (name, addr))   — cross-node: RemoteChannelReader over the
                            writer process's direct server
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.dag.channel import (Channel, ChannelClosedError,
                                 RemoteChannelReader, SlotView)

# device-edge descriptor: the channel carries this tiny dict; the tensor
# stays in the producer's device store (reference
# torch_tensor_accelerator_channel.py: metadata via shm, payload
# out-of-band)
DEVICE_DESC = "__rtpu_device_oid__"


def materialize_channel_value(value):
    """Resolve a channel payload: device descriptors fetch the living
    tensor through the device-object plane (same-process zero-copy, ICI
    between gang members, snapshot otherwise)."""
    if isinstance(value, dict) and DEVICE_DESC in value:
        import ray_tpu
        from ray_tpu.core.ids import ObjectID
        from ray_tpu.core.object_ref import ObjectRef

        return ray_tpu.get(ObjectRef(ObjectID(value[DEVICE_DESC])))
    return value


def _ref_key(ref) -> tuple:
    kind, val = ref
    if kind == "chan":
        return (kind, val)
    name, addr = val
    return (kind, name, (addr[0], int(addr[1])))


def exec_dag_loop(instance: Any, schedule: List[dict]) -> int:
    from concurrent.futures import ThreadPoolExecutor

    readers: Dict[tuple, Any] = {}
    writers: Dict[str, Channel] = {}
    # overlap scheduling (reference dag_node_operation.py
    # overlap_gpu_communication): reads of channels this actor does NOT
    # produce earlier in the same iteration are issued CONCURRENTLY up
    # front, so a remote edge's RPC latency overlaps other edges' reads
    # and the first steps' compute. Self-produced channels must be read
    # in program order (write-then-read same iteration).
    own_outs = {st["out_chan"] for st in schedule if st["out_chan"]}
    prefetchable = set()
    for st in schedule:
        for ref in list(st["args"]) + list(st["kwargs"].values()):
            if ref[0] in ("chan", "rchan"):
                key = _ref_key(ref)
                name = ref[1] if ref[0] == "chan" else ref[1][0]
                if name not in own_outs:
                    prefetchable.add((key, ref[0], name, 
                                      ref[1] if ref[0] == "chan"
                                      else tuple(ref[1][1])))
    pool = (ThreadPoolExecutor(max_workers=min(8, max(1, len(prefetchable))),
                               thread_name_prefix="dag-prefetch")
            if len(prefetchable) > 1 else None)

    def reader(ref) -> Any:
        key = _ref_key(ref)
        if key not in readers:
            if ref[0] == "chan":
                readers[key] = Channel.attach(ref[1])
            else:
                name, addr = ref[1]
                readers[key] = RemoteChannelReader(name, addr)
        return readers[key]

    def writer(name: str) -> Channel:
        if name not in writers:
            writers[name] = Channel.attach(name)
        return writers[name]

    # attach everything up front so the first iteration doesn't race
    # execution (the channels themselves were all created before any loop
    # started — two-phase bring-up in CompiledDAG._start)
    for step in schedule:
        for ref in list(step["args"]) + list(step["kwargs"].values()):
            if ref[0] in ("chan", "rchan"):
                reader(ref)
        if step["out_chan"]:
            writer(step["out_chan"])

    iterations = 0
    # device-edge lifetime: the producer holds the ONLY refs to its
    # device outputs. Ring backpressure bounds reader lag to num_slots
    # values, so num_slots + 2 generations stay alive (the slots a
    # reader may still be fetching plus the value just written) —
    # released as newer writes land.
    from collections import deque as _deque

    dev_refs: Dict[str, "_deque"] = {}
    try:
        while True:
            # one channel may feed several steps in an iteration: read once
            read_cache: Dict[tuple, Any] = {}
            futures = {}
            # local channels are consumed ZERO-COPY (read_zc): step inputs
            # alias the ring slot, which stays pinned — the writer cannot
            # overwrite it — until the views are released below, AFTER
            # every step of the iteration has run and written its output
            # (the output write serializes into its own slot, so nothing
            # aliasing an input survives the release). Remote edges keep
            # read() — their bytes already crossed an RPC.
            pending_views: List[SlotView] = []
            if pool is not None:
                for key, kind, name, addr in prefetchable:
                    r = reader((kind, name if kind == "chan"
                                else (name, addr)))
                    futures[key] = pool.submit(
                        r.read_zc if isinstance(r, Channel) else r.read)

            def fetch(ref) -> Any:
                key = _ref_key(ref)
                if key not in read_cache:
                    if key in futures:
                        value = futures.pop(key).result()
                    else:
                        r = reader(ref)
                        value = (r.read_zc() if isinstance(r, Channel)
                                 else r.read())
                    if isinstance(value, SlotView):
                        pending_views.append(value)
                        value = value.value()
                    read_cache[key] = materialize_channel_value(value)
                return read_cache[key]

            try:
                for step in schedule:
                    args = [fetch((kind, v)) if kind in ("chan", "rchan")
                            else v
                            for kind, v in step["args"]]
                    kwargs = {k: (fetch((kind, v))
                                  if kind in ("chan", "rchan") else v)
                              for k, (kind, v) in step["kwargs"].items()}
                    result = getattr(instance, step["method"])(*args,
                                                               **kwargs)
                    out = step["out_chan"]
                    if out:
                        if step.get("transport") == "device":
                            from ray_tpu.core.api import _global_client

                            oref = _global_client().put_device(result)
                            gens = dev_refs.setdefault(out, _deque())
                            gens.append(oref)
                            keep = writer(out).num_slots + 2
                            while len(gens) > keep:
                                gens.popleft()   # GC -> dec -> device free
                            result = {DEVICE_DESC: oref.binary()}
                        # same-actor downstream steps re-read the channel
                        # (their ack is counted in num_readers); single-slot
                        # channels support read-after-write in the same
                        # thread because the pinned input views released at
                        # iteration END belong to OTHER channels (a step
                        # reads its own output only after writing it this
                        # iteration)
                        writer(out).write(result)
            finally:
                for sv in pending_views:
                    sv.release()
            iterations += 1
    except ChannelClosedError:
        dev_refs.clear()   # release held device outputs
        return iterations
    finally:
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
