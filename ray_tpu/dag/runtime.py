"""Actor-side compiled-DAG loop (reference do_exec_tasks,
compiled_dag_node.py:191): attach edge channels, then loop
READ -> COMPUTE -> WRITE until the driver closes the channels.

Edge refs come in two flavors (resolved by the driver's placement pass):
("chan", name)            — same-node: attach the shm channel directly
("rchan", (name, addr))   — cross-node: RemoteChannelReader over the
                            writer process's direct server
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.dag.channel import (Channel, ChannelClosedError,
                                 RemoteChannelReader)


def _ref_key(ref) -> tuple:
    kind, val = ref
    if kind == "chan":
        return (kind, val)
    name, addr = val
    return (kind, name, (addr[0], int(addr[1])))


def exec_dag_loop(instance: Any, schedule: List[dict]) -> int:
    readers: Dict[tuple, Any] = {}
    writers: Dict[str, Channel] = {}

    def reader(ref) -> Any:
        key = _ref_key(ref)
        if key not in readers:
            if ref[0] == "chan":
                readers[key] = Channel.attach(ref[1])
            else:
                name, addr = ref[1]
                readers[key] = RemoteChannelReader(name, addr)
        return readers[key]

    def writer(name: str) -> Channel:
        if name not in writers:
            writers[name] = Channel.attach(name)
        return writers[name]

    # attach everything up front so the first iteration doesn't race
    # execution (the channels themselves were all created before any loop
    # started — two-phase bring-up in CompiledDAG._start)
    for step in schedule:
        for ref in list(step["args"]) + list(step["kwargs"].values()):
            if ref[0] in ("chan", "rchan"):
                reader(ref)
        if step["out_chan"]:
            writer(step["out_chan"])

    iterations = 0
    try:
        while True:
            # one channel may feed several steps in an iteration: read once
            read_cache: Dict[tuple, Any] = {}

            def fetch(ref) -> Any:
                key = _ref_key(ref)
                if key not in read_cache:
                    read_cache[key] = reader(ref).read()
                return read_cache[key]

            for step in schedule:
                args = [fetch((kind, v)) if kind in ("chan", "rchan") else v
                        for kind, v in step["args"]]
                kwargs = {k: (fetch((kind, v)) if kind in ("chan", "rchan")
                              else v)
                          for k, (kind, v) in step["kwargs"].items()}
                result = getattr(instance, step["method"])(*args, **kwargs)
                out = step["out_chan"]
                if out:
                    # same-actor downstream steps re-read the channel (their
                    # ack is counted in num_readers); single-slot channels
                    # support read-after-write in the same thread
                    writer(out).write(result)
            iterations += 1
    except ChannelClosedError:
        return iterations
