"""Actor-side compiled-DAG loop (reference do_exec_tasks,
compiled_dag_node.py:191): attach edge channels, then loop
READ -> COMPUTE -> WRITE until the driver closes the channels."""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.dag.channel import Channel, ChannelClosedError


def exec_dag_loop(instance: Any, schedule: List[dict]) -> int:
    chans: Dict[str, Channel] = {}

    def chan(name: str) -> Channel:
        if name not in chans:
            chans[name] = Channel.attach(name)
        return chans[name]

    # attach everything up front so the first iteration doesn't race creation
    for step in schedule:
        for kind, val in list(step["args"]) + list(step["kwargs"].values()):
            if kind == "chan":
                chan(val)
        if step["out_chan"]:
            chan(step["out_chan"])

    iterations = 0
    try:
        while True:
            # one channel may feed several steps in an iteration: read once
            read_cache: Dict[str, Any] = {}

            def fetch(name: str) -> Any:
                if name not in read_cache:
                    read_cache[name] = chan(name).read()
                return read_cache[name]

            for step in schedule:
                args = [fetch(v) if kind == "chan" else v
                        for kind, v in step["args"]]
                kwargs = {k: (fetch(v) if kind == "chan" else v)
                          for k, (kind, v) in step["kwargs"].items()}
                result = getattr(instance, step["method"])(*args, **kwargs)
                out = step["out_chan"]
                if out:
                    # same-actor downstream steps re-read the channel (their
                    # ack is counted in num_readers); single-slot channels
                    # support read-after-write in the same thread
                    chan(out).write(result)
            iterations += 1
    except ChannelClosedError:
        return iterations
