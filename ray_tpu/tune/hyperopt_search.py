"""HyperOpt (TPE) searcher adapter (optional dependency).

Parity target: `python/ray/tune/search/hyperopt/hyperopt_search.py` —
an ask/tell bridge over hyperopt's Trials machinery: each suggest()
inserts a new TPE-proposed trial document, completions are written back
as hyperopt results. hyperopt is NOT bundled: constructing
HyperOptSearch without it raises ImportError with install guidance
(reference behavior).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.search import (Choice, Domain, GridSearch, LogUniform,
                                 RandInt, Uniform)
from ray_tpu.tune.searcher import Searcher


class HyperOptSearch(Searcher):
    def __init__(self, n_initial_points: int = 20,
                 seed: Optional[int] = None, gamma: float = 0.25):
        try:
            import hyperopt as hpo
        except ImportError as e:  # pragma: no cover - depends on env
            raise ImportError(
                "HyperOptSearch requires `hyperopt` "
                "(pip install hyperopt)") from e
        import numpy as np

        self._hpo = hpo
        self._algo = lambda *args: hpo.tpe.suggest(
            *args, n_startup_jobs=n_initial_points, gamma=gamma)
        self._rstate = np.random.default_rng(seed)
        self._trials = None           # hyperopt.Trials
        self._domain = None           # hyperopt.Domain over the space
        self._open: Dict[str, int] = {}   # our trial_id -> hyperopt tid

    # ------------------------------------------------------------ space
    def _to_hp_space(self, param_space: Dict[str, Any]) -> dict:
        hp = self._hpo.hp
        space = {}
        self._constants = {}
        for k, v in param_space.items():
            if isinstance(v, Uniform):
                space[k] = hp.uniform(k, v.low, v.high)
            elif isinstance(v, LogUniform):
                import math

                space[k] = hp.loguniform(k, math.log(v.low),
                                         math.log(v.high))
            elif isinstance(v, RandInt):
                space[k] = hp.randint(k, v.low, v.high)
            elif isinstance(v, (Choice, GridSearch)):
                vals = v.categories if isinstance(v, Choice) else v.values
                space[k] = hp.choice(k, list(vals))
            else:
                self._constants[k] = v
        return space

    def set_search_properties(self, metric, mode, param_space):
        super().set_search_properties(metric, mode, param_space)
        space = self._to_hp_space(param_space)
        self._trials = self._hpo.Trials()
        self._domain = self._hpo.Domain(lambda spc: spc, space)

    # ---------------------------------------------------------- ask/tell
    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        h = self._hpo
        new_ids = self._trials.new_trial_ids(1)
        self._trials.refresh()
        seed = int(self._rstate.integers(2 ** 31 - 1))
        new_trials = self._algo(new_ids, self._domain, self._trials, seed)
        self._trials.insert_trial_docs(new_trials)
        self._trials.refresh()
        tid = new_trials[0]["tid"]
        self._open[trial_id] = tid
        vals = {k: v[0] for k, v in
                new_trials[0]["misc"]["vals"].items() if v}
        cfg = h.space_eval(self._domain.expr, vals)
        out = dict(self._constants)
        out.update(cfg)
        return out

    def _tell(self, trial_id: str, loss: Optional[float],
              ok: bool) -> None:
        h = self._hpo
        tid = self._open.pop(trial_id, None)
        if tid is None or self._trials is None:
            return
        for t in self._trials._dynamic_trials:
            if t["tid"] == tid:
                t["state"] = h.JOB_STATE_DONE if ok else h.JOB_STATE_ERROR
                if ok:
                    t["result"] = {"loss": loss, "status": h.STATUS_OK}
                else:
                    t["result"] = {"status": h.STATUS_FAIL}
                break
        self._trials.refresh()

    def on_trial_complete(self, trial_id, metrics=None, error=False):
        if error or metrics is None or self.metric not in metrics:
            self._tell(trial_id, None, ok=False)
            return
        value = float(metrics[self.metric])
        loss = value if self.mode == "min" else -value
        self._tell(trial_id, loss, ok=True)
