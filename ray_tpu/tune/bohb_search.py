"""BOHB searcher: KDE-model-based suggestions (TPE-style density ratio).

Behavioral parity with `python/ray/tune/search/bohb/bohb_search.py`
(TuneBOHB, which wraps hpbandster's ConfigSpace + KDE model): completed
trials split at a quantile into good/bad sets; new configs are sampled
around good points and ranked by the good/bad kernel-density ratio
l(x)/g(x) — the BOHB paper's model. Pair with ASHAScheduler /
HyperbandForBOHB-style early stopping via TuneConfig.scheduler (the
bracket machinery already lives in tune/schedulers.py). Implemented in
numpy; no hpbandster/ConfigSpace dependency.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.tune.bayesopt_search import _Dim
from ray_tpu.tune.search import Domain, GridSearch
from ray_tpu.tune.searcher import Searcher


class BOHBSearch(Searcher):
    def __init__(self, min_points_in_model: int = 6,
                 top_n_fraction: float = 0.3, bandwidth: float = 0.12,
                 n_candidates: int = 64, random_fraction: float = 0.2,
                 seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)
        self.min_points = min_points_in_model
        self.top_frac = top_n_fraction
        self.bw = bandwidth
        self.n_candidates = n_candidates
        self.random_fraction = random_fraction
        self._dims: List[_Dim] = []
        self._constants: Dict[str, Any] = {}
        self._X: List[np.ndarray] = []
        self._y: List[float] = []
        self._open: Dict[str, np.ndarray] = {}

    def set_search_properties(self, metric, mode, param_space):
        super().set_search_properties(metric, mode, param_space)
        self._dims = []
        self._constants = {}
        for k, v in param_space.items():
            if isinstance(v, (Domain, GridSearch)):
                self._dims.append(_Dim(k, v))
            else:
                self._constants[k] = v

    def _kde(self, points: np.ndarray, x: np.ndarray) -> np.ndarray:
        """Gaussian KDE density of candidates `x` under `points`."""
        d2 = ((x[:, None, :] - points[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.bw ** 2)).mean(1) + 1e-12

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        d = len(self._dims)
        if d == 0:
            return dict(self._constants)
        if (len(self._X) < self.min_points
                or self._rng.random() < self.random_fraction):
            # BOHB keeps a random fraction for exploration even with a
            # full model (paper §3; reference random_fraction)
            u = self._rng.random(d)
        else:
            X = np.stack(self._X)
            y = np.asarray(self._y)
            n_good = max(1, int(self.top_frac * len(y)))
            order = np.argsort(-y)      # maximize internally
            good, bad = X[order[:n_good]], X[order[n_good:]]
            if len(bad) == 0:
                bad = X
            # sample candidates AROUND good points (hpbandster samples
            # from the good KDE), rank by density ratio
            seeds = good[self._rng.integers(len(good), size=self.n_candidates)]
            cand = np.clip(
                seeds + self._rng.normal(0, self.bw, seeds.shape), 0, 1)
            ratio = self._kde(good, cand) / self._kde(bad, cand)
            u = cand[int(np.argmax(ratio))]
        self._open[trial_id] = u
        cfg = {dim.key: dim.from_unit(float(u[i]))
               for i, dim in enumerate(self._dims)}
        cfg.update(self._constants)
        return cfg

    def on_trial_complete(self, trial_id, metrics=None, error=False):
        u = self._open.pop(trial_id, None)
        if u is None or error or not metrics or self.metric not in metrics:
            return
        score = float(metrics[self.metric])
        if self.mode == "min":
            score = -score
        self._X.append(u)
        self._y.append(score)
