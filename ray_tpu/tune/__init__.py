"""ray_tpu.tune: hyperparameter optimization (reference: python/ray/tune/,
SURVEY §2.7). `tune.report` shares the train session (a trial IS a 1-worker
train run, matching the reference's Trainable/Train unification in v2)."""

from ray_tpu.train.session import get_context, report  # noqa: F401
from ray_tpu.tune.schedulers import (ASHAScheduler, FIFOScheduler,
                                     MedianStoppingRule, PB2,
                                     PopulationBasedTraining)
from ray_tpu.tune.search import (BasicVariantGenerator, choice, grid_search,
                                 loguniform, randint, uniform)
from ray_tpu.tune.searcher import RandomSearcher, Searcher
from ray_tpu.tune.optuna_search import OptunaSearch
from ray_tpu.tune.hyperopt_search import HyperOptSearch
from ray_tpu.tune.bayesopt_search import BayesOptSearch
from ray_tpu.tune.bohb_search import BOHBSearch
from ray_tpu.tune.tuner import (ResultGrid, TrialResult, TuneConfig, Tuner,
                                with_resources)


def get_checkpoint():
    return get_context().get_checkpoint()


__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "TrialResult", "with_resources",
    "report", "get_checkpoint", "get_context",
    "choice", "uniform", "loguniform", "randint", "grid_search",
    "BasicVariantGenerator", "FIFOScheduler", "ASHAScheduler",
    "MedianStoppingRule", "PopulationBasedTraining", "PB2",
    "Searcher", "RandomSearcher", "OptunaSearch", "HyperOptSearch",
    "BayesOptSearch", "BOHBSearch",
]
