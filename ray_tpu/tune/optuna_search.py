"""Optuna searcher adapter (optional dependency).

Parity target: `python/ray/tune/search/optuna/optuna_search.py` — an
ask/tell bridge: each suggest() is `study.ask()` with distributions
derived from the tune search space; completions are `study.tell()`.
Optuna is NOT bundled: constructing OptunaSearch without it installed
raises ImportError with install guidance (reference behavior).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.search import (Choice, Domain, GridSearch, LogUniform,
                                 RandInt, Uniform)
from ray_tpu.tune.searcher import Searcher


class OptunaSearch(Searcher):
    def __init__(self, sampler: Any = None, seed: Optional[int] = None):
        try:
            import optuna
        except ImportError as e:  # pragma: no cover - depends on env
            raise ImportError(
                "OptunaSearch requires `optuna` (pip install optuna)"
            ) from e
        self._optuna = optuna
        if sampler is None:
            sampler = optuna.samplers.TPESampler(seed=seed)
        self._sampler = sampler
        self._study = None
        self._trials: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, param_space):
        super().set_search_properties(metric, mode, param_space)
        direction = "minimize" if mode == "min" else "maximize"
        self._study = self._optuna.create_study(direction=direction,
                                                sampler=self._sampler)

    def _suggest_param(self, ot, key: str, dom: Any):
        if isinstance(dom, Uniform):
            return ot.suggest_float(key, dom.low, dom.high)
        if isinstance(dom, LogUniform):
            return ot.suggest_float(key, dom.low, dom.high, log=True)
        if isinstance(dom, RandInt):
            return ot.suggest_int(key, dom.low, dom.high - 1)
        if isinstance(dom, (Choice, GridSearch)):
            vals = dom.categories if isinstance(dom, Choice) else dom.values
            idx = ot.suggest_categorical(f"{key}__idx",
                                         list(range(len(vals))))
            return vals[idx]
        return dom  # constant

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        ot = self._study.ask()
        self._trials[trial_id] = ot
        cfg = {}
        for k, v in self.param_space.items():
            cfg[k] = (self._suggest_param(ot, k, v)
                      if isinstance(v, (Domain, GridSearch)) else v)
        return cfg

    def on_trial_complete(self, trial_id, metrics=None, error=False):
        ot = self._trials.pop(trial_id, None)
        if ot is None:
            return
        state = self._optuna.trial.TrialState.COMPLETE
        value = None
        if error or not metrics or self.metric not in metrics:
            state = self._optuna.trial.TrialState.FAIL
        else:
            value = float(metrics[self.metric])
        self._study.tell(ot, value, state=state)
