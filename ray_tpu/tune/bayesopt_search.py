"""Gaussian-process Bayesian optimization searcher (self-contained).

Behavioral parity with `python/ray/tune/search/bayesopt/bayesopt_search.py`
(which wraps the `bayesian-optimization` package): a GP surrogate with an
RBF kernel over the unit-cube-normalized search space, expected-improvement
acquisition maximized over random candidates. Implemented in numpy — no
external dependency (same approach as the r4 PB2 GP-bandit).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.tune.search import (Choice, Domain, GridSearch, LogUniform,
                                 RandInt, Uniform)
from ray_tpu.tune.searcher import Searcher


class _Dim:
    """One normalized dimension: maps config value <-> [0, 1]."""

    def __init__(self, key: str, dom: Any):
        self.key = key
        self.dom = dom
        self.categories: Optional[List[Any]] = None
        if isinstance(dom, (Choice, GridSearch)):
            self.categories = list(dom.categories if isinstance(dom, Choice)
                                   else dom.values)

    def to_unit(self, v: Any) -> float:
        d = self.dom
        if self.categories is not None:
            return self.categories.index(v) / max(len(self.categories) - 1, 1)
        if isinstance(d, LogUniform):
            return ((math.log(v) - math.log(d.low))
                    / (math.log(d.high) - math.log(d.low)))
        if isinstance(d, (Uniform, RandInt)):
            return (v - d.low) / max(d.high - d.low, 1e-12)
        return 0.0

    def from_unit(self, u: float) -> Any:
        d = self.dom
        u = min(max(u, 0.0), 1.0)
        if self.categories is not None:
            idx = int(round(u * (len(self.categories) - 1)))
            return self.categories[idx]
        if isinstance(d, LogUniform):
            return math.exp(math.log(d.low)
                            + u * (math.log(d.high) - math.log(d.low)))
        if isinstance(d, RandInt):
            return int(d.low + u * (d.high - 1 - d.low) + 0.5)
        return d.low + u * (d.high - d.low)


class BayesOptSearch(Searcher):
    def __init__(self, n_initial_points: int = 5, kappa_seed: Optional[int] = None,
                 seed: Optional[int] = None, n_candidates: int = 512,
                 length_scale: float = 0.25, noise: float = 1e-4):
        self._rng = np.random.default_rng(
            seed if seed is not None else kappa_seed)
        self.n_initial = n_initial_points
        self.n_candidates = n_candidates
        self.ls = length_scale
        self.noise = noise
        self._dims: List[_Dim] = []
        self._constants: Dict[str, Any] = {}
        self._X: List[np.ndarray] = []     # observed unit points
        self._y: List[float] = []          # observed scores (maximize)
        self._open: Dict[str, np.ndarray] = {}

    def set_search_properties(self, metric, mode, param_space):
        super().set_search_properties(metric, mode, param_space)
        self._dims = []
        self._constants = {}
        for k, v in param_space.items():
            if isinstance(v, (Domain, GridSearch)):
                self._dims.append(_Dim(k, v))
            else:
                self._constants[k] = v

    # ---------------------------------------------------------------- GP
    def _kernel(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.ls ** 2))

    def _ei(self, cand: np.ndarray) -> np.ndarray:
        """Expected improvement of candidates over the incumbent."""
        X = np.stack(self._X)
        y = np.asarray(self._y)
        ymean, ystd = y.mean(), max(y.std(), 1e-9)
        yn = (y - ymean) / ystd
        K = self._kernel(X, X) + self.noise * np.eye(len(X))
        Ks = self._kernel(cand, X)
        try:
            L = np.linalg.cholesky(K)
            alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
            v = np.linalg.solve(L, Ks.T)
            mu = Ks @ alpha
            var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        except np.linalg.LinAlgError:
            return self._rng.random(len(cand))
        sigma = np.sqrt(var)
        best = yn.max()
        z = (mu - best) / sigma
        # standard normal pdf/cdf
        pdf = np.exp(-0.5 * z ** 2) / math.sqrt(2 * math.pi)
        cdf = 0.5 * (1 + np.vectorize(math.erf)(z / math.sqrt(2)))
        return (mu - best) * cdf + sigma * pdf

    # ---------------------------------------------------------- ask/tell
    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        d = len(self._dims)
        if d == 0:
            return dict(self._constants)
        if len(self._X) < self.n_initial:
            u = self._rng.random(d)
        else:
            cand = self._rng.random((self.n_candidates, d))
            u = cand[int(np.argmax(self._ei(cand)))]
        self._open[trial_id] = u
        cfg = {dim.key: dim.from_unit(float(u[i]))
               for i, dim in enumerate(self._dims)}
        cfg.update(self._constants)
        return cfg

    def on_trial_complete(self, trial_id, metrics=None, error=False):
        u = self._open.pop(trial_id, None)
        if u is None or error or not metrics or self.metric not in metrics:
            return
        score = float(metrics[self.metric])
        if self.mode == "min":
            score = -score
        self._X.append(u)
        self._y.append(score)
