"""Search spaces + suggestion generators.

Parity (core subset) with `python/ray/tune/search/`: sample-space primitives
(uniform/loguniform/randint/choice/grid_search) and BasicVariantGenerator
(grid cross-product × random sampling); concurrency is capped by
`TuneConfig.max_concurrent_trials`.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import random
from typing import Any, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclasses.dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclasses.dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclasses.dataclass
class RandInt(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclasses.dataclass
class Choice(Domain):
    categories: List[Any]

    def sample(self, rng):
        return rng.choice(self.categories)


@dataclasses.dataclass
class GridSearch:
    values: List[Any]


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(categories: List[Any]) -> Choice:
    return Choice(list(categories))


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(list(values))


class BasicVariantGenerator:
    """Grid axes form a cross product; Domain axes are sampled per variant
    (reference search/basic_variant.py)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self) -> Iterator[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if isinstance(v, GridSearch)]
        grid_values = [self.param_space[k].values for k in grid_keys]
        grids = list(itertools.product(*grid_values)) or [()]
        for _ in range(self.num_samples):
            for combo in grids:
                cfg = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self.rng)
                    else:
                        cfg[k] = v
                yield cfg
