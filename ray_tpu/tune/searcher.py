"""Searcher seam: pluggable suggestion algorithms for the Tuner.

Parity target: the reference's `Searcher` interface
(`python/ray/tune/search/searcher.py` — suggest/on_trial_complete) and its
external integrations (`tune/search/optuna/optuna_search.py` etc.). The
built-in `BasicVariantGenerator` stays the default; a `Searcher` set on
`TuneConfig.search_alg` turns trial generation sequential-adaptive: each
new trial's config is suggested from the live results of finished ones.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ray_tpu.tune.search import Choice, Domain, GridSearch


class Searcher:
    """Suggestion algorithm interface (reference searcher.py)."""

    # sentinel return from suggest(): the search space is exhausted and no
    # further trials will ever be suggested (reference Searcher.FINISHED)
    FINISHED = "FINISHED"

    def set_search_properties(self, metric: str, mode: str,
                              param_space: Dict[str, Any]) -> None:
        self.metric = metric
        self.mode = mode
        self.param_space = param_space

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """Next config to try; None = no suggestion RIGHT NOW (retry
        later); Searcher.FINISHED = permanently done."""
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          metrics: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        """Final result (or failure) of a suggested trial."""

    def on_trial_result(self, trial_id: str,
                        metrics: Dict[str, Any]) -> None:
        """Intermediate result (optional for pruners)."""


class RandomSearcher(Searcher):
    """Domain-sampling searcher — the simplest concrete Searcher; also the
    CI stand-in proving the seam without external deps."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        cfg = {}
        for k, v in self.param_space.items():
            if isinstance(v, GridSearch):
                cfg[k] = self._rng.choice(v.values)
            elif isinstance(v, Domain):
                cfg[k] = v.sample(self._rng)
            else:
                cfg[k] = v
        return cfg
