"""Tuner + trial controller.

Parity (core subset) with `python/ray/tune/tuner.py` +
`execution/tune_controller.py`: an event loop managing trial actors (the
TrainWorker actor is reused as the trial host — same report/poll/stop
surface), searchers generating variants, schedulers deciding early stops and
PBT exploits, per-trial checkpoint tracking, ResultGrid output.
"""

from __future__ import annotations

import dataclasses
import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.train.worker_group import TrainWorker
from ray_tpu.tune import schedulers as sched_lib
from ray_tpu.tune.search import BasicVariantGenerator

POLL_S = 0.1


@dataclasses.dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 8
    scheduler: Optional[Any] = None
    seed: Optional[int] = None


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    error: Optional[str]
    history: List[Dict[str, Any]]


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: str, mode: str):
        self.results = results
        self._metric = metric
        self._mode = mode

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self.results
                  if r.error is None and metric in (r.metrics or {})]
        if not scored:
            raise ValueError("no successful trials with the target metric")
        key = lambda r: r.metrics[metric]
        return (min if mode == "min" else max)(scored, key=key)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([
            {"trial_id": r.trial_id, **r.config, **(r.metrics or {}),
             "error": bool(r.error)} for r in self.results])

    def __len__(self):
        return len(self.results)


class _Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.id = trial_id
        self.config = config
        self.actor = None
        self.state = "PENDING"
        self.iteration = 0
        self.last_metrics: Dict[str, Any] = {}
        self.history: List[Dict[str, Any]] = []
        self.error: Optional[str] = None
        self.checkpoint_path: Optional[str] = None
        self.resume_path: Optional[str] = None


def with_resources(trainable: Callable, resources: Dict[str, float]):
    trainable._tune_resources = dict(resources)
    return trainable


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: Dict[str, Any],
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self.trainable = trainable
        self.param_space = param_space
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig(name=f"tune-{uuid.uuid4().hex[:6]}")

    # ------------------------------------------------------------------ fit
    def fit(self) -> ResultGrid:
        from ray_tpu.core.api import _auto_init

        _auto_init()
        storage = self.run_config.resolved_storage_path()
        os.makedirs(storage, exist_ok=True)
        scheduler = self.tune_config.scheduler or sched_lib.FIFOScheduler()
        gen = BasicVariantGenerator(self.param_space,
                                    self.tune_config.num_samples,
                                    seed=self.tune_config.seed)
        trials = [_Trial(f"trial_{i:04d}", cfg)
                  for i, cfg in enumerate(gen.variants())]
        pending = list(trials)
        running: List[_Trial] = []
        resources = getattr(self.trainable, "_tune_resources", {"CPU": 1})

        while pending or running:
            while pending and len(running) < self.tune_config.max_concurrent_trials:
                t = pending.pop(0)
                try:
                    self._start_trial(t, resources)
                except Exception as e:
                    # per-trial failure: mark this trial errored, keep tuning
                    t.state = "ERRORED"
                    t.error = f"trial failed to start: {e!r}"
                    self._stop_actor(t)
                    continue
                running.append(t)
            time.sleep(POLL_S)
            for t in list(running):
                try:
                    st = ray_tpu.get(t.actor.poll.remote(), timeout=30)
                except Exception:
                    t.state = "ERRORED"
                    t.error = "trial actor died"
                    running.remove(t)
                    self._stop_actor(t)
                    continue
                decision = sched_lib.CONTINUE
                for rep in st["reports"]:
                    t.iteration += 1
                    metrics = dict(rep["metrics"])
                    metrics.setdefault("training_iteration", t.iteration)
                    t.last_metrics = metrics
                    t.history.append(metrics)
                    if rep["checkpoint_path"]:
                        t.checkpoint_path = rep["checkpoint_path"]
                    d = scheduler.on_result(t.id, metrics)
                    if d != sched_lib.CONTINUE:
                        decision = d
                if st["error"]:
                    t.state = "ERRORED"
                    t.error = st["error"]
                    running.remove(t)
                    self._stop_actor(t)
                elif st["done"]:
                    t.state = "COMPLETED"
                    running.remove(t)
                    self._stop_actor(t)
                elif decision == sched_lib.STOP:
                    t.state = "STOPPED"
                    running.remove(t)
                    self._stop_actor(t)
                elif isinstance(decision, tuple) and decision[0] == "EXPLOIT":
                    _, donor_id, mutate = decision
                    donor = next(d for d in trials if d.id == donor_id)
                    try:
                        self._exploit(t, donor, mutate)
                    except Exception as e:
                        t.state = "ERRORED"
                        t.error = f"exploit restart failed: {e!r}"
                        running.remove(t)
                        self._stop_actor(t)
        results = [TrialResult(
            trial_id=t.id, config=t.config, metrics=t.last_metrics,
            checkpoint=Checkpoint(t.checkpoint_path) if t.checkpoint_path else None,
            error=t.error, history=t.history) for t in trials]
        return ResultGrid(results, self.tune_config.metric,
                          self.tune_config.mode)

    # -------------------------------------------------------------- helpers
    def _start_trial(self, t: _Trial, resources: Dict[str, float]) -> None:
        t.actor = TrainWorker.options(
            resources=resources, num_cpus=resources.get("CPU", 0),
            name=f"{self.run_config.name}-{t.id}-{uuid.uuid4().hex[:4]}").remote()
        ray_tpu.get(t.actor.setup_and_start.remote(
            self.trainable, t.config, 0, 1, 0, 0, t.resume_path, {}),
            timeout=120)
        t.state = "RUNNING"

    def _stop_actor(self, t: _Trial) -> None:
        if t.actor is not None:
            try:
                ray_tpu.kill(t.actor)
            except Exception:
                pass
            t.actor = None

    def _exploit(self, t: _Trial, donor: "_Trial", mutate) -> None:
        """PBT: restart `t` from donor's checkpoint with mutated config."""
        self._stop_actor(t)
        t.config = mutate(donor.config)
        t.resume_path = donor.checkpoint_path
        resources = getattr(self.trainable, "_tune_resources", {"CPU": 1})
        self._start_trial(t, resources)
