"""Tuner + trial controller.

Parity (core subset) with `python/ray/tune/tuner.py` +
`execution/tune_controller.py`: an event loop managing trial actors (the
TrainWorker actor is reused as the trial host — same report/poll/stop
surface), searchers generating variants, schedulers deciding early stops and
PBT exploits, per-trial checkpoint tracking, ResultGrid output.
"""

from __future__ import annotations

import dataclasses
import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.train.worker_group import TrainWorker
from ray_tpu.tune import schedulers as sched_lib
from ray_tpu.tune.search import BasicVariantGenerator
from ray_tpu.tune.searcher import Searcher

POLL_S = 0.1


@dataclasses.dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 8
    scheduler: Optional[Any] = None
    seed: Optional[int] = None
    # a Searcher makes trial generation sequential-adaptive: each new
    # trial's config is suggested from live results of finished ones
    # (reference TuneConfig.search_alg)
    search_alg: Optional[Any] = None


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    error: Optional[str]
    history: List[Dict[str, Any]]


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: str, mode: str):
        self.results = results
        self._metric = metric
        self._mode = mode

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self.results
                  if r.error is None and metric in (r.metrics or {})]
        if not scored:
            raise ValueError("no successful trials with the target metric")
        key = lambda r: r.metrics[metric]
        return (min if mode == "min" else max)(scored, key=key)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([
            {"trial_id": r.trial_id, **r.config, **(r.metrics or {}),
             "error": bool(r.error)} for r in self.results])

    def __len__(self):
        return len(self.results)


class _Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.id = trial_id
        self.config = config
        self.actor = None
        self.state = "PENDING"
        self.iteration = 0
        self.last_metrics: Dict[str, Any] = {}
        self.history: List[Dict[str, Any]] = []
        self.error: Optional[str] = None
        self.checkpoint_path: Optional[str] = None
        self.resume_path: Optional[str] = None


def with_resources(trainable: Callable, resources: Dict[str, float]):
    trainable._tune_resources = dict(resources)
    return trainable


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: Dict[str, Any],
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self.trainable = trainable
        self.param_space = param_space
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig(name=f"tune-{uuid.uuid4().hex[:6]}")

    # ------------------------------------------------------------------ fit
    def fit(self) -> ResultGrid:
        from ray_tpu.core.api import _auto_init

        _auto_init()
        storage = self.run_config.resolved_storage_path()
        os.makedirs(storage, exist_ok=True)
        scheduler = self.tune_config.scheduler or sched_lib.FIFOScheduler()
        searcher = self.tune_config.search_alg
        if searcher is not None:
            from ray_tpu.tune.search import GridSearch

            grid_keys = [k for k, v in self.param_space.items()
                         if isinstance(v, GridSearch)]
            if grid_keys:
                # a searcher samples; it cannot honor exhaustive-grid
                # semantics — failing loudly beats silently skipping
                # grid values (reference raises the same way)
                raise ValueError(
                    f"grid_search params {grid_keys} cannot be combined "
                    f"with a search_alg; use tune.choice() instead")
            searcher.set_search_properties(self.tune_config.metric,
                                           self.tune_config.mode,
                                           self.param_space)
            trials: List[_Trial] = []
            pending: List[_Trial] = []
        else:
            gen = BasicVariantGenerator(self.param_space,
                                        self.tune_config.num_samples,
                                        seed=self.tune_config.seed)
            trials = [_Trial(f"trial_{i:04d}", cfg)
                      for i, cfg in enumerate(gen.variants())]
            pending = list(trials)
        running: List[_Trial] = []
        resources = getattr(self.trainable, "_tune_resources", {"CPU": 1})

        def _searcher_complete(t: "_Trial") -> None:
            if searcher is None:
                return
            try:
                searcher.on_trial_complete(
                    t.id, metrics=t.last_metrics or None,
                    error=t.state == "ERRORED")
            except Exception:
                pass

        searcher_finished = searcher is None
        while (pending or running
               or (not searcher_finished
                   and len(trials) < self.tune_config.num_samples)):
            # adaptive generation: ask the searcher for the next config
            # only when a slot opens, so suggestions see fresh completions
            while (not searcher_finished
                   and len(trials) < self.tune_config.num_samples
                   and len(running) + len(pending)
                   < self.tune_config.max_concurrent_trials):
                tid = f"trial_{len(trials):04d}"
                try:
                    cfg = searcher.suggest(tid)
                except Exception as e:
                    # a broken searcher must not abort fit() mid-run and
                    # orphan the live trial actors
                    print(f"[ray_tpu.tune] searcher.suggest failed, "
                          f"stopping generation: {e!r}")
                    searcher_finished = True
                    break
                if cfg is None:
                    break  # searcher is not ready; retry next tick
                if cfg is Searcher.FINISHED or cfg == Searcher.FINISHED:
                    searcher_finished = True  # space exhausted for good
                    break
                t = _Trial(tid, cfg)
                trials.append(t)
                pending.append(t)
            while pending and len(running) < self.tune_config.max_concurrent_trials:
                t = pending.pop(0)
                try:
                    self._start_trial(t, resources)
                except Exception as e:
                    # per-trial failure: mark this trial errored, keep tuning
                    t.state = "ERRORED"
                    t.error = f"trial failed to start: {e!r}"
                    self._stop_actor(t)
                    _searcher_complete(t)
                    continue
                running.append(t)
            time.sleep(POLL_S)
            for t in list(running):
                try:
                    st = ray_tpu.get(t.actor.poll.remote(), timeout=30)
                except Exception:
                    t.state = "ERRORED"
                    t.error = "trial actor died"
                    running.remove(t)
                    self._stop_actor(t)
                    _searcher_complete(t)
                    continue
                decision = sched_lib.CONTINUE
                for rep in st["reports"]:
                    t.iteration += 1
                    metrics = dict(rep["metrics"])
                    metrics.setdefault("training_iteration", t.iteration)
                    t.last_metrics = metrics
                    t.history.append(metrics)
                    if rep["checkpoint_path"]:
                        t.checkpoint_path = rep["checkpoint_path"]
                    if searcher is not None:
                        try:
                            searcher.on_trial_result(t.id, metrics)
                        except Exception:
                            pass
                    # schedulers see the live config too (PB2's GP models
                    # config -> score improvement); user metrics stay clean.
                    # Once a batch produced a decision, trailing reports
                    # are NOT fed onward: the trial is about to stop or
                    # restart, and PB2's exploit cleanup must not be
                    # undone by stale same-batch reports.
                    if decision == sched_lib.CONTINUE:
                        d = scheduler.on_result(
                            t.id, {**metrics, "config": t.config})
                        if d != sched_lib.CONTINUE:
                            decision = d
                if st["error"]:
                    t.state = "ERRORED"
                    t.error = st["error"]
                    running.remove(t)
                    self._stop_actor(t)
                    _searcher_complete(t)
                elif st["done"]:
                    t.state = "COMPLETED"
                    running.remove(t)
                    self._stop_actor(t)
                    _searcher_complete(t)
                elif decision == sched_lib.STOP:
                    t.state = "STOPPED"
                    running.remove(t)
                    self._stop_actor(t)
                    _searcher_complete(t)
                elif isinstance(decision, tuple) and decision[0] == "EXPLOIT":
                    _, donor_id, mutate = decision
                    donor = next(d for d in trials if d.id == donor_id)
                    try:
                        self._exploit(t, donor, mutate)
                    except Exception as e:
                        t.state = "ERRORED"
                        t.error = f"exploit restart failed: {e!r}"
                        running.remove(t)
                        self._stop_actor(t)
                        _searcher_complete(t)
        results = [TrialResult(
            trial_id=t.id, config=t.config, metrics=t.last_metrics,
            checkpoint=Checkpoint(t.checkpoint_path) if t.checkpoint_path else None,
            error=t.error, history=t.history) for t in trials]
        return ResultGrid(results, self.tune_config.metric,
                          self.tune_config.mode)

    # -------------------------------------------------------------- helpers
    def _start_trial(self, t: _Trial, resources: Dict[str, float]) -> None:
        t.actor = TrainWorker.options(
            resources=resources, num_cpus=resources.get("CPU", 0),
            name=f"{self.run_config.name}-{t.id}-{uuid.uuid4().hex[:4]}").remote()
        ray_tpu.get(t.actor.setup_and_start.remote(
            self.trainable, t.config, 0, 1, 0, 0, t.resume_path, {}),
            timeout=120)
        t.state = "RUNNING"

    def _stop_actor(self, t: _Trial) -> None:
        if t.actor is not None:
            try:
                ray_tpu.kill(t.actor)
            except Exception:
                pass
            t.actor = None

    def _exploit(self, t: _Trial, donor: "_Trial", mutate) -> None:
        """PBT: restart `t` from donor's checkpoint with mutated config."""
        self._stop_actor(t)
        t.config = mutate(donor.config)
        t.resume_path = donor.checkpoint_path
        resources = getattr(self.trainable, "_tune_resources", {"CPU": 1})
        self._start_trial(t, resources)
