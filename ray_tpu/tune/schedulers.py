"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Parity (core subset) with `python/ray/tune/schedulers/`: ASHA
(`async_hyperband.py` rung-based promotion), MedianStoppingRule, and
PopulationBasedTraining (exploit top quantile + mutate, restart from donor
checkpoint).
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        pass


class ASHAScheduler(FIFOScheduler):
    """Async successive halving: at rungs grace_period * rf^k, stop trials
    outside the top 1/reduction_factor of results seen at that rung."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.rung_results: Dict[int, List[float]] = defaultdict(list)
        # rungs each trial has already been judged at (milestone crossing is
        # evaluated once per rung per trial, like the reference ASHA)
        self._judged: Dict[str, set] = defaultdict(set)

    def _better(self, a: float) -> float:
        return a if self.mode == "min" else -a

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr)
        score = result.get(self.metric)
        if t is None or score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        # judge at the largest rung <= t not yet seen for this trial; exact
        # equality would silently no-op for time_attrs that skip rung values
        for rung in reversed(self.rungs):
            if t >= rung and rung not in self._judged[trial_id]:
                self._judged[trial_id].add(rung)
                recorded = self.rung_results[rung]
                recorded.append(self._better(float(score)))
                k = max(1, len(recorded) // self.rf)
                cutoff = sorted(recorded)[k - 1]
                if self._better(float(score)) > cutoff:
                    return STOP
                break
        return CONTINUE


class MedianStoppingRule(FIFOScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same step."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 1,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.time_attr = time_attr
        self.history: Dict[str, List[float]] = defaultdict(list)

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        score = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if score is None:
            return CONTINUE
        sign = 1.0 if self.mode == "min" else -1.0
        self.history[trial_id].append(sign * float(score))
        if t < self.grace or len(self.history) < 3:
            return CONTINUE
        my_avg = sum(self.history[trial_id]) / len(self.history[trial_id])
        others = [sum(v) / len(v) for k, v in self.history.items()
                  if k != trial_id]
        others.sort()
        median = others[len(others) // 2]
        return STOP if my_avg > median else CONTINUE


class PopulationBasedTraining(FIFOScheduler):
    """PBT (reference schedulers/pbt.py): every perturbation_interval
    results, bottom-quantile trials adopt a top-quantile trial's config
    (mutated) and checkpoint. The controller executes the decision
    ("EXPLOIT", donor_trial_id, new_config)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 2,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25, seed: Optional[int] = None,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self.rng = random.Random(seed)
        self.latest: Dict[str, float] = {}
        self.counts: Dict[str, int] = defaultdict(int)

    def on_result(self, trial_id: str, result: Dict[str, Any]):
        score = result.get(self.metric)
        if score is None:
            return CONTINUE
        sign = -1.0 if self.mode == "min" else 1.0
        self.latest[trial_id] = sign * float(score)
        self.counts[trial_id] += 1
        if self.counts[trial_id] % self.interval or len(self.latest) < 4:
            return CONTINUE
        ranked = sorted(self.latest, key=self.latest.get, reverse=True)
        k = max(1, int(len(ranked) * self.quantile))
        if trial_id in ranked[-k:]:
            donor = self.rng.choice(ranked[:k])
            if donor != trial_id:
                return ("EXPLOIT", donor, self._mutate)
        return CONTINUE

    def _mutate(self, donor_config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search import Domain

        cfg = dict(donor_config)
        for key, spec in self.mutations.items():
            if isinstance(spec, list):
                cfg[key] = self.rng.choice(spec)
            elif isinstance(spec, Domain):
                cfg[key] = spec.sample(self.rng)
            elif callable(spec):
                cfg[key] = spec()
            elif key in cfg and isinstance(cfg[key], (int, float)):
                cfg[key] = cfg[key] * self.rng.choice([0.8, 1.2])
        return cfg
