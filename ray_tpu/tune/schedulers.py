"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Parity (core subset) with `python/ray/tune/schedulers/`: ASHA
(`async_hyperband.py` rung-based promotion), MedianStoppingRule, and
PopulationBasedTraining (exploit top quantile + mutate, restart from donor
checkpoint).
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        pass


class ASHAScheduler(FIFOScheduler):
    """Async successive halving: at rungs grace_period * rf^k, stop trials
    outside the top 1/reduction_factor of results seen at that rung."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.rung_results: Dict[int, List[float]] = defaultdict(list)
        # rungs each trial has already been judged at (milestone crossing is
        # evaluated once per rung per trial, like the reference ASHA)
        self._judged: Dict[str, set] = defaultdict(set)

    def _better(self, a: float) -> float:
        return a if self.mode == "min" else -a

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr)
        score = result.get(self.metric)
        if t is None or score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        # judge at the largest rung <= t not yet seen for this trial; exact
        # equality would silently no-op for time_attrs that skip rung values
        for rung in reversed(self.rungs):
            if t >= rung and rung not in self._judged[trial_id]:
                self._judged[trial_id].add(rung)
                recorded = self.rung_results[rung]
                recorded.append(self._better(float(score)))
                k = max(1, len(recorded) // self.rf)
                cutoff = sorted(recorded)[k - 1]
                if self._better(float(score)) > cutoff:
                    return STOP
                break
        return CONTINUE


class MedianStoppingRule(FIFOScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same step."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 1,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.time_attr = time_attr
        self.history: Dict[str, List[float]] = defaultdict(list)

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        score = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if score is None:
            return CONTINUE
        sign = 1.0 if self.mode == "min" else -1.0
        self.history[trial_id].append(sign * float(score))
        if t < self.grace or len(self.history) < 3:
            return CONTINUE
        my_avg = sum(self.history[trial_id]) / len(self.history[trial_id])
        others = [sum(v) / len(v) for k, v in self.history.items()
                  if k != trial_id]
        others.sort()
        median = others[len(others) // 2]
        return STOP if my_avg > median else CONTINUE


class PopulationBasedTraining(FIFOScheduler):
    """PBT (reference schedulers/pbt.py): every perturbation_interval
    results, bottom-quantile trials adopt a top-quantile trial's config
    (mutated) and checkpoint. The controller executes the decision
    ("EXPLOIT", donor_trial_id, new_config)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 2,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25, seed: Optional[int] = None,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self.rng = random.Random(seed)
        self.latest: Dict[str, float] = {}
        self.counts: Dict[str, int] = defaultdict(int)

    def on_result(self, trial_id: str, result: Dict[str, Any]):
        import math

        score = result.get(self.metric)
        if score is None or not math.isfinite(float(score)):
            # a diverged trial's nan would give it an arbitrary rank —
            # possibly top-quantile, exploiting healthy trials onto it
            return CONTINUE
        sign = -1.0 if self.mode == "min" else 1.0
        self.latest[trial_id] = sign * float(score)
        self.counts[trial_id] += 1
        if self.counts[trial_id] % self.interval or len(self.latest) < 4:
            return CONTINUE
        ranked = sorted(self.latest, key=self.latest.get, reverse=True)
        k = max(1, int(len(ranked) * self.quantile))
        if trial_id in ranked[-k:]:
            donor = self.rng.choice(ranked[:k])
            if donor != trial_id:
                return ("EXPLOIT", donor, self._mutate)
        return CONTINUE

    def _mutate(self, donor_config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search import Domain

        cfg = dict(donor_config)
        for key, spec in self.mutations.items():
            if isinstance(spec, list):
                cfg[key] = self.rng.choice(spec)
            elif isinstance(spec, Domain):
                cfg[key] = spec.sample(self.rng)
            elif callable(spec):
                cfg[key] = spec()
            elif key in cfg and isinstance(cfg[key], (int, float)):
                cfg[key] = cfg[key] * self.rng.choice([0.8, 1.2])
        return cfg


class PB2(PopulationBasedTraining):
    """PB2: PBT whose exploit step picks new hyperparameters with a
    GP-bandit (UCB) over observed (config -> score improvement) data,
    instead of random perturbation. Parity: `python/ray/tune/schedulers/
    pb2.py` (Parker-Holder et al., NeurIPS 2020) — re-implemented on a
    small numpy Gaussian process (RBF kernel), no GPy dependency.

    `hyperparam_bounds` maps each tuned key to (low, high); values are
    optimized in normalized [0,1]^d space. Categorical keys stay with
    PBT-style resampling via `hyperparam_mutations`.
    """

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 2,
                 hyperparam_bounds: Optional[Dict[str, Any]] = None,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25, seed: Optional[int] = None,
                 time_attr: str = "training_iteration", ucb_kappa: float = 2.0):
        super().__init__(metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations=hyperparam_mutations,
                         quantile_fraction=quantile_fraction, seed=seed,
                         time_attr=time_attr)
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in (hyperparam_bounds or {}).items()}
        self.kappa = ucb_kappa
        # observations: (normalized config vector, score delta since the
        # trial's previous window) — what the GP models
        self._obs_x: list = []
        self._obs_y: list = []
        self._prev_score: Dict[str, float] = {}
        self._trial_cfg: Dict[str, Dict[str, Any]] = {}

    # the controller tells us each trial's live config via on_result's
    # carried config when available; fall back to donor config at exploit
    def record_config(self, trial_id: str, config: Dict[str, Any]) -> None:
        self._trial_cfg[trial_id] = dict(config)

    def _normalize(self, cfg: Dict[str, Any]):
        import numpy as _np

        return _np.asarray([
            ((float(cfg.get(k, lo)) - lo) / (hi - lo) if hi > lo else 0.0)
            for k, (lo, hi) in sorted(self.bounds.items())], dtype=float)

    def on_result(self, trial_id: str, result: Dict[str, Any]):
        cfg = result.get("config")
        if isinstance(cfg, dict):
            self.record_config(trial_id, cfg)
        score = result.get(self.metric)
        if score is not None and trial_id in self._trial_cfg and self.bounds:
            sign = -1.0 if self.mode == "min" else 1.0
            s = sign * float(score)
            import math

            prev = self._prev_score.get(trial_id)
            if prev is not None and math.isfinite(s - prev):
                # one diverged trial's nan would poison every UCB pick
                self._obs_x.append(self._normalize(self._trial_cfg[trial_id]))
                self._obs_y.append(s - prev)
                if len(self._obs_y) > 256:   # GP only reads the tail
                    del self._obs_x[:-128], self._obs_y[:-128]
            if math.isfinite(s):
                self._prev_score[trial_id] = s
        decision = super().on_result(trial_id, result)
        if isinstance(decision, tuple) and decision[0] == "EXPLOIT":
            # the exploited trial restarts from the DONOR's checkpoint:
            # its next score jump is inherited, not earned by the freshly
            # GP-picked config — never feed it to the GP as improvement
            self._prev_score.pop(trial_id, None)
            self._trial_cfg.pop(trial_id, None)
        return decision

    # ------------------------------------------------------------- GP-UCB
    def _gp_ucb_pick(self):
        """Maximize UCB of predicted score-improvement over [0,1]^d via
        random candidate search (d is small for hyperparams)."""
        import numpy as _np

        d = len(self.bounds)
        rng = _np.random.default_rng(self.rng.randrange(1 << 30))
        cands = rng.random((256, d))
        if len(self._obs_y) < 3:
            return cands[0]
        X = _np.stack(self._obs_x[-64:])
        y = _np.asarray(self._obs_y[-64:], dtype=float)
        y_std = y.std() or 1.0
        y = (y - y.mean()) / y_std
        ls, noise = 0.3, 1e-3

        def k(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return _np.exp(-d2 / (2 * ls * ls))

        K = k(X, X) + noise * _np.eye(len(X))
        Kinv = _np.linalg.inv(K)
        Ks = k(cands, X)
        mu = Ks @ Kinv @ y
        var = _np.clip(1.0 - (Ks @ Kinv * Ks).sum(-1), 1e-9, None)
        ucb = mu + self.kappa * _np.sqrt(var)
        return cands[int(_np.argmax(ucb))]

    def _mutate(self, donor_config: Dict[str, Any]) -> Dict[str, Any]:
        # categorical keys resample PBT-style (hyperparam_mutations);
        # continuous bounded keys come from the GP-UCB pick
        cfg = super()._mutate(donor_config) if self.mutations \
            else dict(donor_config)
        if not self.bounds:
            return cfg
        z = self._gp_ucb_pick()
        for i, (key, (lo, hi)) in enumerate(sorted(self.bounds.items())):
            cfg[key] = lo + float(z[i]) * (hi - lo)
        return cfg
