"""Distributed hash/range shuffle: map tasks partition, reduce tasks merge.

Parity: `python/ray/data/_internal/execution/operators/hash_shuffle.py` and
the push-based shuffle in `_internal/planner/exchange/` — a two-stage
all-to-all where no block ever lands on the driver:

  map stage:    one task per input block → P keyed sub-blocks
                (multi-return task, one ObjectRef per sub-block)
  reduce stage: one task per output partition ← the P-th ref of every map

The reduce task receives sub-blocks through the object store directly
(worker-to-worker), so the driver only handles ObjectRefs.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from ray_tpu.data.block import (Block, block_concat, block_len, block_slice,
                                block_to_batch, rows_of, to_numpy_columns)


def _partition_block(block: Block, assign: np.ndarray, P: int) -> List[Block]:
    """Split rows into P sub-blocks per the assignment vector."""
    block = to_numpy_columns(block)  # barriers materialize numpy
    out: List[Block] = []
    if isinstance(block, dict):
        for p in range(P):
            idx = np.nonzero(assign == p)[0]
            out.append({k: np.asarray(v)[idx] for k, v in block.items()})
    else:
        rows = list(block)
        buckets: List[List[Any]] = [[] for _ in range(P)]
        for r, p in zip(rows, assign):
            buckets[int(p)].append(r)
        out = buckets
    return out


def _hash_of(values) -> np.ndarray:
    """Stable vectorized hash (don't use Python hash(): salted per process)."""
    arr = np.asarray(values)
    if arr.dtype.kind in "iub":
        v = arr.astype(np.uint64)
        v = (v ^ (v >> 33)) * np.uint64(0xFF51AFD7ED558CCD)
        v = (v ^ (v >> 33)) * np.uint64(0xC4CEB9FE1A85EC53)
        return v ^ (v >> 33)
    import zlib

    return np.asarray([zlib.crc32(str(x).encode()) for x in arr],
                      dtype=np.uint64)


def _map_partition(source, ops, P: int, mode: str, key: Optional[str],
                   seed: Optional[int], boundaries):
    """Map-stage body: run the fused op chain, then split into P parts."""
    from ray_tpu.data.dataset import _exec_chain

    block = to_numpy_columns(_exec_chain(source, ops))
    n = block_len(block)
    if n == 0:
        parts = _partition_block(block, np.zeros(0, np.int64), P)
    elif mode == "hash":
        if isinstance(block, dict):
            keys = block[key]
        else:
            keys = [r[key] for r in rows_of(block)]
        assign = (_hash_of(keys) % np.uint64(P)).astype(np.int64)
        parts = _partition_block(block, assign, P)
    elif mode == "random":
        rng = np.random.default_rng(seed)
        assign = rng.integers(0, P, size=n)
        parts = _partition_block(block, assign, P)
    elif mode == "range":
        if isinstance(block, dict):
            keys = np.asarray(block[key])
        else:
            keys = np.asarray([r[key] for r in rows_of(block)])
        assign = np.searchsorted(np.asarray(boundaries), keys, side="right")
        parts = _partition_block(block, assign, P)
    elif mode == "round_robin":
        assign = np.arange(n) % P
        parts = _partition_block(block, assign, P)
    elif mode == "offset":
        # rows assigned by global row index against cumulative boundaries
        # (seed carries this block's global start offset; zip resharding)
        idx = int(seed or 0) + np.arange(n)
        assign = np.searchsorted(np.asarray(boundaries), idx, side="right")
        parts = _partition_block(block, assign, P)
    else:
        raise ValueError(mode)
    return tuple(parts) if P > 1 else parts[0]


def _reduce_concat(*parts):
    parts = [to_numpy_columns(p) for p in parts]
    return block_concat([p for p in parts if block_len(p)])


def _reduce_shuffled(seed, *parts):
    """Concat then permute rows — without this, rows keep their relative
    order inside each output partition and 'shuffled' data stays
    near-sorted (a silent training-data bug)."""
    block = _reduce_concat(*parts)
    n = block_len(block)
    perm = np.random.default_rng(seed).permutation(n)
    if isinstance(block, dict):
        return {k: np.asarray(v)[perm] for k, v in block.items()}
    rows = list(block)
    return [rows[i] for i in perm]


def _reduce_sorted(key, descending, *parts):
    block = _reduce_concat(*parts)
    if isinstance(block, dict):
        order = np.argsort(block[key], kind="stable")
        if descending:
            order = order[::-1]
        return {k: np.asarray(v)[order] for k, v in block.items()}
    return sorted(block, key=lambda r: r[key], reverse=descending)


def shuffle_refs(partitions: List[Any], ops: List[Any], P: int, mode: str,
                 *, key: Optional[str] = None, seed: Optional[int] = None,
                 boundaries=None,
                 reduce_fn: Optional[Callable] = None,
                 reduce_extra_args: tuple = ()) -> List[Any]:
    """Run the two-stage shuffle; returns P ObjectRefs of reduced blocks.

    Fault tolerance: map tasks are multi-return and head-submitted, so
    every sub-block has a lineage ledger entry; reduce tasks opt into
    out-of-band lineage (`lineage=True`, they ride the lease path). A
    node SIGKILLed mid-shuffle loses only its resident sub-blocks — the
    reduce tasks' dependency fetches park at the head, which re-runs
    exactly the map tasks whose outputs died (lazy reconstruction,
    surfaced as data_blocks_reconstructed_total), and the shuffle
    completes byte-identical."""
    import ray_tpu

    map_task = ray_tpu.remote(_map_partition).options(
        num_returns=P, name="data_shuffle_map", data_stage=True)
    reducer = ray_tpu.remote(reduce_fn or _reduce_concat).options(
        name="data_shuffle_reduce", lineage=True, data_stage=True)
    map_out = []
    for i, src in enumerate(partitions):
        # salt the seed per map task: identical seeds would send row t of
        # every equal-sized block to the same partition
        task_seed = None if seed is None else seed + 7919 * i
        if mode == "random" and seed is None:
            task_seed = np.random.randint(1 << 31) + i
        refs = map_task.remote(src, ops, P, mode, key, task_seed, boundaries)
        map_out.append([refs] if P == 1 else refs)
    out = []
    for p in range(P):
        cols = [m[p] for m in map_out]
        out.append(reducer.remote(*reduce_extra_args, *cols))
    return out


def block_lens(partitions, ops=()) -> List[int]:
    """Row count per partition via tiny remote tasks (only ints reach the
    driver)."""
    import ray_tpu

    def len_of(source, ops):
        from ray_tpu.data.dataset import _exec_chain

        return block_len(_exec_chain(source, list(ops)))

    if not ray_tpu.is_initialized():
        from ray_tpu.data.dataset import _exec_chain

        return [block_len(_exec_chain(s, list(ops))) for s in partitions]
    task = ray_tpu.remote(len_of)
    return ray_tpu.get([task.remote(s, list(ops)) for s in partitions])


def sample_boundaries(partitions: List[Any], ops: List[Any], key: str,
                      P: int, sample_size: int = 256) -> np.ndarray:
    """Range-partition boundaries from per-block samples (the reference's
    sort sampling in `_internal/planner/exchange/sort_task_spec.py`)."""
    import ray_tpu

    def sample_one(source, ops, key, k):
        from ray_tpu.data.dataset import _exec_chain

        block = _exec_chain(source, ops)
        if isinstance(block, dict):
            vals = np.asarray(block[key])
        else:
            vals = np.asarray([r[key] for r in rows_of(block)])
        if len(vals) > k:
            vals = np.random.default_rng(0).choice(vals, size=k, replace=False)
        return vals

    task = ray_tpu.remote(sample_one)
    samples = ray_tpu.get([task.remote(s, ops, key, sample_size)
                           for s in partitions])
    allv = np.sort(np.concatenate([s for s in samples if len(s)]))
    if len(allv) == 0:
        return np.zeros(P - 1)
    qs = np.linspace(0, len(allv) - 1, P + 1)[1:-1].astype(int)
    return allv[qs]
