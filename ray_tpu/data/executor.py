"""Operator-graph streaming executor for Data pipelines.

Behavioral parity with the reference's StreamingExecutor
(`python/ray/data/_internal/execution/streaming_executor.py:61`,
`streaming_executor_state.py` Topology/OpState,
`backpressure_policy/concurrency_cap_backpressure_policy.py`): the op
chain lowers to a Topology of physical stages, each with its own input
queue, in-flight cap, and stats; the scheduling loop admits work to ANY
stage with capacity, so a block can be in stage 3 while another is still
in stage 1 — inter-operator concurrency, not a fused per-block chain.

Fault tolerance & the data plane (ISSUE 15): inter-stage blocks are
directory-announced objects pulled through node PullManagers — the
executor ships dep metas with each dispatch (zero get_meta round trips
warm) and prefetches a completed block into the consuming stage's node
before its task dispatches. Every stage task registers its spec in the
head's lineage ledger (`options(lineage=True, data_stage=True)`), so a
block lost to node death is lazily rebuilt by re-running exactly its
producing task; a consumer task that surfaces ObjectLostError (its INPUT
died mid-flight) is retried by the executor instead of failing the
pipeline. Backpressure is two-signal: a congested downstream queue sheds
upstream admission, and gossiped store-pressure rows
(`ClusterView.max_store_frac`) stop stage-0 input admission before the
cluster store OOMs. Consumed intermediates release their lineage entries
eagerly (per-partition chain release) so a long pipeline's footprint
stays bounded by the in-flight window.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ray_tpu.core import config as _config

_GAUGES: Dict[str, Any] = {}


def _metrics() -> Dict[str, Any]:
    """Live per-operator series in the cluster metrics registry (the
    reference streaming executor's Gauge set, streaming_executor.py:105)
    — visible at /metrics as ray_tpu_data_*. ONE shared object per name
    (stages are tag values): per-stage objects would overwrite each
    other in the registry."""
    from ray_tpu.util import metrics as _m

    if not _GAUGES:
        _GAUGES["in_flight"] = _m.Gauge(
            "data_op_in_flight", "Data operator in-flight block tasks",
            tag_keys=("op",))
        _GAUGES["queued"] = _m.Gauge(
            "data_op_queued", "Data operator queued blocks",
            tag_keys=("op",))
        _GAUGES["backpressure"] = _m.Counter(
            "data_backpressure_total",
            "Admission ticks shed by live-signal backpressure",
            tag_keys=("op", "reason"))
        _GAUGES["retries"] = _m.Counter(
            "data_input_retries_total",
            "Pipeline consumer tasks retried after their input block "
            "went lost (rides lineage reconstruction)", tag_keys=("op",))
        _GAUGES["prefetch"] = _m.Counter(
            "data_prefetch_total",
            "Blocks staged into the consuming stage's node ahead of "
            "dispatch", tag_keys=("op",))
    return _GAUGES


def _op_gauges(stage: "Stage", in_flight: int, queued: int) -> None:
    try:
        m = _metrics()
        m["in_flight"].set(in_flight, {"op": stage.name})
        m["queued"].set(queued, {"op": stage.name})
    except Exception:
        pass   # metrics must never break execution


class OpStats:
    """Per-operator execution counters (reference OpState metrics +
    `Dataset.stats()` per-op rows)."""

    def __init__(self, name: str):
        self.name = name
        self.submitted = 0
        self.completed = 0
        self.bytes_out = 0
        self.retried = 0        # resubmits after a lost-input failure
        self.prefetches = 0     # blocks staged ahead of dispatch
        self.throttled = 0      # admission ticks shed by backpressure
        self.first_submit_ts: Optional[float] = None
        self.last_complete_ts: Optional[float] = None
        # (submit_ts, complete_ts) per block — the overlap evidence
        self.intervals: List[Tuple[float, float]] = []
        self._open: Dict[Any, float] = {}

    def on_submit(self, ref: Any) -> None:
        now = time.monotonic()
        self.submitted += 1
        if self.first_submit_ts is None:
            self.first_submit_ts = now
        self._open[ref] = now

    def on_complete(self, ref: Any, nbytes: int) -> None:
        now = time.monotonic()
        self.completed += 1
        self.bytes_out += nbytes
        self.last_complete_ts = now
        start = self._open.pop(ref, now)
        self.intervals.append((start, now))

    def summary(self) -> str:
        wall = ((self.last_complete_ts or 0) - (self.first_submit_ts or 0))
        out = (f"{self.name}: {self.completed} blocks, "
               f"{self.bytes_out / 1e6:.2f} MB, {wall:.3f}s busy")
        extras = []
        if self.retried:
            extras.append(f"{self.retried} retried")
        if self.prefetches:
            extras.append(f"{self.prefetches} prefetched")
        if self.throttled:
            extras.append(f"{self.throttled} throttled")
        if extras:
            out += " (" + ", ".join(extras) + ")"
        return out


class Stage:
    """One physical operator: turns an upstream block ref into a
    downstream block ref. `max_in_flight` is the per-op concurrency cap
    (reference ConcurrencyCapBackpressurePolicy)."""

    def __init__(self, name: str, max_in_flight: int = 16):
        self.name = name
        self.max_in_flight = max_in_flight
        self.stats = OpStats(name)

    def submit(self, ref: Any) -> Any:
        raise NotImplementedError

    def prefetch_target(self):
        """Data-server address of the node this stage's next task will
        run on, or None (no prefetch)."""
        return None

    def close(self) -> None:
        pass


class TaskStage(Stage):
    """Fused chain of per-block task ops (reference TaskPoolMapOperator;
    adjacent map/filter/flat_map fuse into ONE task — the physical-plan
    fusion rule). Tasks carry `lineage=True` so the head can re-run them
    when their output block is lost, and `data_stage=True` so those
    reconstructions count into data_blocks_reconstructed_total."""

    def __init__(self, ops: List[Any], max_in_flight: int = 16):
        names = ",".join(o.kind for o in ops) or "read"
        super().__init__(f"Map({names})", max_in_flight)
        import ray_tpu
        from ray_tpu.data.dataset import _exec_chain

        self._task = ray_tpu.remote(_exec_chain).options(
            name=f"data:{names or 'read'}", lineage=True, data_stage=True)
        self._ops = ops
        self._pf_cache: Tuple[float, Any] = (0.0, None)

    def submit(self, ref: Any) -> Any:
        return self._task.remote(ref, self._ops)

    def prefetch_target(self):
        """The current lease's node for this task shape, resolved from
        cache and memoized briefly (leases are sticky; re-resolving per
        block would cost a lock + view scan each)."""
        now = time.monotonic()
        ts, addr = self._pf_cache
        if now - ts < 2.0:
            return addr
        addr = None
        try:
            from ray_tpu.core.api import _build_resources, _global_client

            client = _global_client()
            fn_key = self._task._ensure_exported()
            addr = client.lease_data_addr(
                fn_key, {"resources": _build_resources(self._task._options)})
        except Exception:
            addr = None
        self._pf_cache = (now, addr)
        return addr


class ActorStage(Stage):
    """Callable-class UDF over a shared actor pool (reference
    ActorPoolMapOperator). In-flight cap = pool size by default: one
    outstanding call per actor keeps the pool busy without queue blowup.
    Round-robin is deterministic, so the prefetch target PEEKS the next
    assignment (`_rr` increments only at submit): input blocks start
    pulling toward the very node whose actor will consume them, like
    lease-path stages."""

    def __init__(self, op: Any):
        super().__init__(f"ActorMap(x{op.concurrency})",
                         max_in_flight=max(op.concurrency, 1))
        from ray_tpu.data.dataset import _BlockActor

        self._op = op
        self.pool = [_BlockActor.remote(op.fn)
                     for _ in range(max(op.concurrency, 1))]
        self._rr = 0
        self._addr_cache: Dict[int, Any] = {}  # pool idx -> data addr

    def submit(self, ref: Any) -> Any:
        actor = self.pool[self._rr % len(self.pool)]
        self._rr += 1
        return actor.apply.remote(ref, self._op.batch_format)

    def prefetch_target(self):
        """Data-server address of the NEXT round-robin actor's node.
        Actors are pinned to their node for life, so resolution (one
        head RPC + a view lookup) is memoized per pool slot."""
        if not self.pool:
            return None
        i = self._rr % len(self.pool)
        if i in self._addr_cache:
            return self._addr_cache[i]
        addr = None
        try:
            from ray_tpu.core.api import _global_client
            from ray_tpu.core.ids import NodeID

            client = _global_client()
            reply = client.head_request(
                "get_actor_address",
                actor_id=self.pool[i]._actor_id.binary())
            node_id = reply.get("node_id")
            if reply.get("state") != "DEAD" and node_id:
                addr = client.cluster_view.data_addr_of(
                    NodeID(node_id).hex())
        except Exception:
            addr = None
        if addr is not None:  # don't cache failures: actor may be pending
            self._addr_cache[i] = addr
        return addr

    def close(self) -> None:
        import ray_tpu

        pool, self.pool = self.pool, []   # idempotent
        for a in pool:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


class StreamingExecutor:
    """Drives a Topology of stages over the input partitions.

    Scheduling loop (reference streaming_executor.py:61): each tick,
    admit queued blocks into every stage with spare in-flight capacity
    (downstream-first, so finished work drains before new work enters),
    then wait for ANY in-flight task across ALL stages and route its
    output to the next stage's queue. Input admission (stage 0) is
    additionally governed by the adaptive byte budget AND the gossiped
    store-pressure signal; inter-stage admission sheds when the
    downstream queue is congested."""

    def __init__(self, stages: List[Stage], partitions: List[Any],
                 input_window: Callable[[], int]):
        self.stages = stages
        self.partitions = partitions
        self.input_window = input_window
        # per-stage input queues of (partition_idx, ref)
        self.queues: List[deque] = [deque() for _ in stages]
        # in-flight output ref -> (partition_idx, input ref) — the input
        # is kept so a lost-input failure can resubmit the same task
        self.in_flight: List[Dict[Any, Tuple[int, Any]]] = [{} for _ in stages]
        self.results: Dict[int, Any] = {}
        # lineage recovery + eager release bookkeeping
        self._retries: Dict[Tuple[int, int], int] = {}
        self.input_retries = 0
        self.prefetches = 0
        self._chain: Dict[int, List[Any]] = {}   # idx -> intermediate refs
        self._released: List[Any] = []           # release batch buffer
        self._prefetch_on = _config.get("data_prefetch")
        self._eager_release = _config.get("data_eager_release")
        self._retry_cap = int(_config.get("data_input_retries"))
        self._highwater = float(_config.get("data_store_highwater"))

    # ------------------------------------------------------- admission
    def _store_hot(self) -> bool:
        """Gossiped store-pressure signal, read entirely from the cached
        cluster view (zero RPCs): True when ANY node's object store runs
        above the highwater fraction."""
        if self._highwater <= 0:
            return False
        try:
            from ray_tpu.core.api import _global_client

            return (_global_client().cluster_view.max_store_frac()
                    >= self._highwater)
        except Exception:
            return False

    def _admit(self) -> None:
        store_hot = self._store_hot()
        for si in range(len(self.stages) - 1, -1, -1):
            stage, q, fl = self.stages[si], self.queues[si], self.in_flight[si]
            cap = stage.max_in_flight
            if si + 1 < len(self.stages):
                # a slow/degraded downstream stage sheds UPSTREAM
                # admission: feeding a stage whose input queue already
                # holds 2x its concurrency only grows store footprint
                nxt = self.stages[si + 1]
                if len(self.queues[si + 1]) >= 2 * nxt.max_in_flight:
                    cap = 0
            if si == 0:
                cap = min(cap, self.input_window())
                if store_hot:
                    # the cluster store is at the highwater: stop
                    # admitting NEW inputs (downstream stages keep
                    # draining, so pressure falls instead of OOMing)
                    cap = 0
            if q and cap <= 0:
                stage.stats.throttled += 1
                try:
                    _metrics()["backpressure"].inc(tags={
                        "op": stage.name,
                        "reason": "store" if (si == 0 and store_hot)
                        else "queue"})
                except Exception:
                    pass
            while q and len(fl) < cap:
                idx, ref = q.popleft()
                out = stage.submit(ref)
                stage.stats.on_submit(out)
                fl[out] = (idx, ref)
            _op_gauges(stage, len(fl), len(q))

    # -------------------------------------------------------- recovery
    def _lost_input(self, client, ref: Any) -> bool:
        """True when a completed stage task's result is an
        ObjectLostError — its INPUT died mid-flight (node loss), which
        is retryable once lineage rebuilds the input — as opposed to a
        user-code failure, which is not."""
        meta = client.local_metas.get(ref.id)
        if meta is None:
            # join the lease call's reply (populates local_metas); a
            # head-scheduled task has no pending call and falls through
            try:
                if client._resolve_pending_call(ref.id, timeout=5):
                    meta = client.local_metas.get(ref.id)
            except Exception:
                meta = client.local_metas.get(ref.id)
        if meta is None:
            # cold-path (head-scheduled) task: one bounded meta lookup —
            # error results are inline and never enter the gossiped
            # directory, so only the head can show the error bit. Lease
            # results carry their meta in the reply, so the warm path
            # never reaches here.
            try:
                meta = client.head_request(
                    "get_meta", object_id=ref.id.binary(), timeout=10)
            except Exception:
                return False
            if meta is not None:
                client.local_metas[ref.id] = meta
        if meta is None or not getattr(meta, "error", False):
            return False
        from ray_tpu.core.exceptions import ObjectLostError

        try:
            client.get([ref])
        except ObjectLostError:
            return True
        except Exception:
            return False
        return False

    def _retry(self, si: int, idx: int, src: Any) -> bool:
        """Resubmit stage si's task for partition idx with the same
        input. The retried task's dependency fetch triggers lineage
        reconstruction of the lost block at the head (get_meta /
        locate_object park until the producer re-runs)."""
        key = (si, idx)
        count = self._retries.get(key, 0)
        if count >= self._retry_cap:
            return False
        self._retries[key] = count + 1
        self.input_retries += 1
        stage = self.stages[si]
        stage.stats.retried += 1
        try:
            _metrics()["retries"].inc(tags={"op": stage.name})
        except Exception:
            pass
        out = stage.submit(src)
        stage.stats.on_submit(out)
        self.in_flight[si][out] = (idx, src)
        return True

    # -------------------------------------------------------- prefetch
    def _prefetch(self, si: int, ref: Any, client) -> None:
        """Stage the block onto the consuming stage's node before its
        task dispatches (ROADMAP item 1 push-side prefetch follow-on,
        delivered on the data plane where it pays): the node PullManager
        dedups with the dispatch-time fetch if they race."""
        if not self._prefetch_on:
            return
        stage = self.stages[si]
        addr = stage.prefetch_target()
        if addr is None:
            return
        try:
            if client.prefetch_object(ref, addr):
                stage.stats.prefetches += 1
                self.prefetches += 1
                _metrics()["prefetch"].inc(tags={"op": stage.name})
        except Exception:
            pass

    # ---------------------------------------------------- eager release
    def release_partition(self, idx: int, final_ref: Any = None) -> None:
        """Called by the consumer once partition idx's final block has
        been fetched: the chain's intermediate (and final) blocks can
        never be needed again, so their lineage entries retire NOW —
        dropping the input dep pins that would otherwise hold every
        intermediate block until cap eviction."""
        refs = self._chain.pop(idx, [])
        if final_ref is not None:
            refs = refs + [final_ref]
        if not refs or not self._eager_release:
            return
        try:
            from ray_tpu.core.api import _global_client

            _global_client().head_push(
                "release_lineage",
                return_ids=[r.id.binary() for r in refs])
        except Exception:
            pass

    # ------------------------------------------------------------- run
    def run(self) -> Iterator[Tuple[int, Any]]:
        """Yields (partition_idx, final block ref) as they complete —
        UNORDERED; the caller handles ordered emission."""
        import ray_tpu
        from ray_tpu.core.api import _global_client

        client = _global_client()
        next_input = 0
        n = len(self.partitions)
        emitted = 0
        try:
            while emitted < n:
                # feed stage-0 queue lazily (partition thunks are cheap
                # handles; real IO happens in the stage task)
                while (next_input < n
                       and len(self.queues[0]) + len(self.in_flight[0])
                       < self.input_window()):
                    self.queues[0].append(
                        (next_input, self.partitions[next_input]))
                    next_input += 1
                self._admit()
                all_refs = [r for fl in self.in_flight for r in fl]
                if not all_refs:
                    if next_input >= n and not any(self.queues):
                        break
                    # queued work shed by backpressure with nothing in
                    # flight: yield the CPU until the signal clears
                    time.sleep(0.02)
                    continue
                ready, _ = ray_tpu.wait(all_refs, num_returns=1, timeout=300)
                for ref in ready:
                    for si, fl in enumerate(self.in_flight):
                        if ref not in fl:
                            continue
                        idx, src = fl.pop(ref)
                        if (self._lost_input(client, ref)
                                and self._retry(si, idx, src)):
                            break
                        # size probe rides the ref; fetching the block
                        # is deferred to the consumer
                        self.stages[si].stats.on_complete(ref, 0)
                        if si + 1 < len(self.stages):
                            self._chain.setdefault(idx, []).append(ref)
                            self.queues[si + 1].append((idx, ref))
                            self._prefetch(si + 1, ref, client)
                        else:
                            emitted += 1
                            yield idx, ref
                        break
        finally:
            self.close()

    def close(self) -> None:
        for s in self.stages:
            s.close()
        self._chain.clear()

    def per_op_stats(self) -> List[OpStats]:
        return [s.stats for s in self.stages]
