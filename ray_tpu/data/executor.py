"""Operator-graph streaming executor for Data pipelines.

Behavioral parity with the reference's StreamingExecutor
(`python/ray/data/_internal/execution/streaming_executor.py:61`,
`streaming_executor_state.py` Topology/OpState,
`backpressure_policy/concurrency_cap_backpressure_policy.py`): the op
chain lowers to a Topology of physical stages, each with its own input
queue, in-flight cap, and stats; the scheduling loop admits work to ANY
stage with capacity, so a block can be in stage 3 while another is still
in stage 1 — inter-operator concurrency, not a fused per-block chain.

Differences from the reference are deliberate: stages run as cluster
tasks/actor calls over ObjectRefs (blocks never pass through the driver),
and the byte-budget backpressure from r4 governs INPUT admission (stage 0)
— the equivalent of the reference's resource-budget policy with the
budget measured from observed completed-block sizes.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple


_GAUGES: Dict[str, Any] = {}


def _op_gauges(stage: "Stage", in_flight: int, queued: int) -> None:
    """Live per-operator gauges into the cluster metrics registry (the
    reference streaming executor's Gauge set, streaming_executor.py:105)
    — visible at /metrics as ray_tpu_data_op_{in_flight,queued}{op}.
    ONE shared gauge per name (stages are tag values): per-stage Gauge
    objects would overwrite each other in the registry."""
    try:
        from ray_tpu.util import metrics as _m

        if not _GAUGES:
            _GAUGES["in_flight"] = _m.Gauge(
                "data_op_in_flight", "Data operator in-flight block tasks",
                tag_keys=("op",))
            _GAUGES["queued"] = _m.Gauge(
                "data_op_queued", "Data operator queued blocks",
                tag_keys=("op",))
        _GAUGES["in_flight"].set(in_flight, {"op": stage.name})
        _GAUGES["queued"].set(queued, {"op": stage.name})
    except Exception:
        pass   # metrics must never break execution


class OpStats:
    """Per-operator execution counters (reference OpState metrics +
    `Dataset.stats()` per-op rows)."""

    def __init__(self, name: str):
        self.name = name
        self.submitted = 0
        self.completed = 0
        self.bytes_out = 0
        self.first_submit_ts: Optional[float] = None
        self.last_complete_ts: Optional[float] = None
        # (submit_ts, complete_ts) per block — the overlap evidence
        self.intervals: List[Tuple[float, float]] = []
        self._open: Dict[Any, float] = {}

    def on_submit(self, ref: Any) -> None:
        now = time.monotonic()
        self.submitted += 1
        if self.first_submit_ts is None:
            self.first_submit_ts = now
        self._open[ref] = now

    def on_complete(self, ref: Any, nbytes: int) -> None:
        now = time.monotonic()
        self.completed += 1
        self.bytes_out += nbytes
        self.last_complete_ts = now
        start = self._open.pop(ref, now)
        self.intervals.append((start, now))

    def summary(self) -> str:
        wall = ((self.last_complete_ts or 0) - (self.first_submit_ts or 0))
        return (f"{self.name}: {self.completed} blocks, "
                f"{self.bytes_out / 1e6:.2f} MB, {wall:.3f}s busy")


class Stage:
    """One physical operator: turns an upstream block ref into a
    downstream block ref. `max_in_flight` is the per-op concurrency cap
    (reference ConcurrencyCapBackpressurePolicy)."""

    def __init__(self, name: str, max_in_flight: int = 16):
        self.name = name
        self.max_in_flight = max_in_flight
        self.stats = OpStats(name)

    def submit(self, ref: Any) -> Any:
        raise NotImplementedError

    def close(self) -> None:
        pass


class TaskStage(Stage):
    """Fused chain of per-block task ops (reference TaskPoolMapOperator;
    adjacent map/filter/flat_map fuse into ONE task — the physical-plan
    fusion rule)."""

    def __init__(self, ops: List[Any], max_in_flight: int = 16):
        names = ",".join(o.kind for o in ops) or "read"
        super().__init__(f"Map({names})", max_in_flight)
        import ray_tpu
        from ray_tpu.data.dataset import _exec_chain

        self._task = ray_tpu.remote(_exec_chain)
        self._ops = ops

    def submit(self, ref: Any) -> Any:
        return self._task.remote(ref, self._ops)


class ActorStage(Stage):
    """Callable-class UDF over a shared actor pool (reference
    ActorPoolMapOperator). In-flight cap = pool size by default: one
    outstanding call per actor keeps the pool busy without queue blowup."""

    def __init__(self, op: Any):
        super().__init__(f"ActorMap(x{op.concurrency})",
                         max_in_flight=max(op.concurrency, 1))
        from ray_tpu.data.dataset import _BlockActor

        self._op = op
        self.pool = [_BlockActor.remote(op.fn)
                     for _ in range(max(op.concurrency, 1))]
        self._rr = 0

    def submit(self, ref: Any) -> Any:
        actor = self.pool[self._rr % len(self.pool)]
        self._rr += 1
        return actor.apply.remote(ref, self._op.batch_format)

    def close(self) -> None:
        import ray_tpu

        pool, self.pool = self.pool, []   # idempotent
        for a in pool:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


class StreamingExecutor:
    """Drives a Topology of stages over the input partitions.

    Scheduling loop (reference streaming_executor.py:61): each tick,
    admit queued blocks into every stage with spare in-flight capacity
    (downstream-first, so finished work drains before new work enters),
    then wait for ANY in-flight task across ALL stages and route its
    output to the next stage's queue. Input admission (stage 0) is
    additionally governed by the adaptive byte budget."""

    def __init__(self, stages: List[Stage], partitions: List[Any],
                 input_window: Callable[[], int]):
        self.stages = stages
        self.partitions = partitions
        self.input_window = input_window
        # per-stage input queues of (partition_idx, ref)
        self.queues: List[deque] = [deque() for _ in stages]
        self.in_flight: List[Dict[Any, int]] = [{} for _ in stages]
        self.results: Dict[int, Any] = {}

    def _admit(self) -> None:
        for si in range(len(self.stages) - 1, -1, -1):
            stage, q, fl = self.stages[si], self.queues[si], self.in_flight[si]
            cap = stage.max_in_flight
            if si == 0:
                cap = min(cap, self.input_window())
            while q and len(fl) < cap:
                idx, ref = q.popleft()
                out = stage.submit(ref)
                stage.stats.on_submit(out)
                fl[out] = idx
            _op_gauges(stage, len(fl), len(q))

    def run(self) -> Iterator[Tuple[int, Any]]:
        """Yields (partition_idx, final block ref) as they complete —
        UNORDERED; the caller handles ordered emission."""
        import ray_tpu

        next_input = 0
        n = len(self.partitions)
        emitted = 0
        try:
            while emitted < n:
                # feed stage-0 queue lazily (partition thunks are cheap
                # handles; real IO happens in the stage task)
                while (next_input < n
                       and len(self.queues[0]) + len(self.in_flight[0])
                       < self.input_window()):
                    self.queues[0].append(
                        (next_input, self.partitions[next_input]))
                    next_input += 1
                self._admit()
                all_refs = [r for fl in self.in_flight for r in fl]
                if not all_refs:
                    if next_input >= n:
                        break
                    continue
                ready, _ = ray_tpu.wait(all_refs, num_returns=1, timeout=300)
                for ref in ready:
                    for si, fl in enumerate(self.in_flight):
                        if ref in fl:
                            idx = fl.pop(ref)
                            # size probe rides the ref; fetching the block
                            # is deferred to the consumer
                            self.stages[si].stats.on_complete(ref, 0)
                            if si + 1 < len(self.stages):
                                self.queues[si + 1].append((idx, ref))
                            else:
                                emitted += 1
                                yield idx, ref
                            break
        finally:
            self.close()

    def close(self) -> None:
        for s in self.stages:
            s.close()

    def per_op_stats(self) -> List[OpStats]:
        return [s.stats for s in self.stages]
