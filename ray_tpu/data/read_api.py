"""Dataset creation: range/from_items/from_numpy + file IO connectors.

Parity (core subset) with `python/ray/data/read_api.py`: parquet/csv/json/
text/binary/numpy readers produce one read thunk per file (or per range
shard), executed lazily by the streaming executor. Paths resolve through
`ray_tpu.utils.fs`, so every reader/writer accepts fsspec URIs
(`gs://`, `s3://`, `memory://`) as well as local paths/globs.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.data.dataset import Dataset
from ray_tpu.utils import fs as _fs

_expand_paths = _fs.expand_paths


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    parallelism = max(1, min(parallelism, n or 1))
    bounds = np.linspace(0, n, parallelism + 1, dtype=np.int64)

    def make(lo: int, hi: int):
        return lambda: {"id": np.arange(lo, hi, dtype=np.int64)}

    return Dataset([make(int(lo), int(hi))
                    for lo, hi in zip(bounds[:-1], bounds[1:])])


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    parallelism = max(1, min(parallelism, len(items) or 1))
    shards = np.array_split(np.arange(len(items)), parallelism)

    def make(idx):
        chunk = [items[i] for i in idx]
        return lambda: chunk

    return Dataset([make(idx) for idx in shards if len(idx)])


def from_numpy(arrays: Dict[str, np.ndarray], *, parallelism: int = 8) -> Dataset:
    n = len(next(iter(arrays.values())))
    bounds = np.linspace(0, n, max(1, parallelism) + 1, dtype=np.int64)

    def make(lo, hi):
        chunk = {k: v[lo:hi] for k, v in arrays.items()}
        return lambda: chunk

    return Dataset([make(int(lo), int(hi))
                    for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo])


def from_pandas(df) -> Dataset:
    return from_numpy({c: df[c].to_numpy() for c in df.columns})


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        import pyarrow.parquet as pq

        # arrow IS a block format: no eager numpy conversion — slices
        # stay zero-copy views, consumers convert per-batch. Local paths
        # go straight to pyarrow (memory-mapped); URIs via fsspec.
        if _fs.is_uri(path):
            with _fs.open(path, "rb") as f:
                return pq.read_table(f, columns=columns)
        return pq.read_table(path, columns=columns)

    return Dataset([functools.partial(read_one, f) for f in files])


def read_csv(paths, **csv_kwargs) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        import pandas as pd

        with _fs.open(path, "r") as f:
            df = pd.read_csv(f, **csv_kwargs)
        return {c: df[c].to_numpy() for c in df.columns}

    return Dataset([functools.partial(read_one, f) for f in files])


def read_json(paths, *, lines: bool = True) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        import json

        rows = []
        with _fs.open(path, "r") as f:
            if lines:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
            else:
                data = json.load(f)
                rows = data if isinstance(data, list) else [data]
        return rows

    return Dataset([functools.partial(read_one, f) for f in files])


def read_text(paths) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        with _fs.open(path, "r") as f:
            return {"text": np.asarray([ln.rstrip("\n") for ln in f],
                                       dtype=object)}

    return Dataset([functools.partial(read_one, f) for f in files])


def read_binary_files(paths) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        with _fs.open(path, "rb") as f:
            return [{"path": path, "bytes": f.read()}]

    return Dataset([functools.partial(read_one, f) for f in files])


def read_numpy(paths) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        with _fs.open(path, "rb") as f:
            arr = np.load(f)
        return {"data": arr}

    return Dataset([functools.partial(read_one, f) for f in files])


IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".gif", ".bmp", ".webp",
                    ".tif", ".tiff")


def read_images(paths, *, size=None, mode: str = "RGB") -> Dataset:
    """Image files → {"image": HWC uint8 array, "path": str} rows
    (reference `ray.data.read_images`). `size=(h, w)` resizes. Directory
    reads skip non-image files (READMEs, labels.csv, ...)."""
    files = [f for f in _expand_paths(paths)
             if f.lower().endswith(IMAGE_EXTENSIONS)]
    if not files:
        raise FileNotFoundError(f"no image files matched {paths}")

    def read_one(path):
        from PIL import Image

        with _fs.open(path, "rb") as f:
            img = Image.open(f).convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        return [{"image": np.asarray(img), "path": path}]

    return Dataset([functools.partial(read_one, f) for f in files])


def from_arrow(tables) -> Dataset:
    """pyarrow Table(s) → Dataset, one block per table, zero-copy
    (reference `ray.data.from_arrow`)."""
    if not isinstance(tables, (list, tuple)):
        tables = [tables]
    return Dataset([t for t in tables])


def from_torch(torch_dataset, *, parallelism: int = 8) -> Dataset:
    """A map-style torch Dataset → row Dataset (reference
    `ray.data.from_torch`): items materialize lazily per partition."""
    n = len(torch_dataset)
    parallelism = max(1, min(parallelism, n or 1))
    bounds = np.linspace(0, n, parallelism + 1, dtype=np.int64)

    def read_slice(lo, hi):
        import builtins

        # NB: this module defines ray-style `range(n)`, shadowing the builtin
        return [{"item": torch_dataset[i]} for i in builtins.range(lo, hi)]

    return Dataset([functools.partial(read_slice, int(lo), int(hi))
                    for lo, hi in zip(bounds[:-1], bounds[1:])])


def from_huggingface(hf_dataset, *, parallelism: int = 8) -> Dataset:
    """A `datasets.Dataset` → Dataset via its arrow table (reference
    `ray.data.from_huggingface`). The datasets library is optional."""
    if getattr(hf_dataset, "_indices", None) is not None:
        # select()/shuffle()/filter() leave an indices mapping over the
        # ORIGINAL table — materialize rows, or we'd return unselected data
        rows = [dict(r) for r in hf_dataset]
        return from_items(rows, parallelism=parallelism)
    try:
        table = hf_dataset.data.table      # arrow-backed: zero-copy
    except AttributeError:
        rows = [dict(r) for r in hf_dataset]
        return from_items(rows, parallelism=parallelism)
    import builtins

    n = max(1, table.num_rows // max(parallelism, 1))
    return Dataset([table.slice(i, n)
                    for i in builtins.range(0, table.num_rows, n)])


def read_sql(sql: str, connection_factory) -> Dataset:
    """A SQL query → one read task over any DBAPI connection factory
    (reference `ray.data.read_sql`). The factory runs INSIDE the read
    task so connections are per-worker, never pickled."""

    def read_all():
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        if not rows:
            return []
        return {c: np.asarray([r[i] for r in rows])
                for i, c in enumerate(cols)}

    return Dataset([read_all])


# ------------------------------------------------------------- tfrecords
def _read_varint(buf: memoryview, pos: int):
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _signed_int64(v: int) -> int:
    """protobuf int64: negatives ride as 10-byte two's-complement varints."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _parse_tf_example(data: bytes) -> dict:
    """Minimal pure-python tf.train.Example parser (wire format only —
    no tensorflow/protobuf dependency; reference read_tfrecords has the
    same no-TF fallback). Returns {feature: list|ndarray}."""
    import struct

    view = memoryview(data)

    def parse_fields(buf, pos, end):
        while pos < end:
            tag, pos = _read_varint(buf, pos)
            field, wire = tag >> 3, tag & 7
            if wire == 2:
                ln, pos = _read_varint(buf, pos)
                yield field, buf[pos:pos + ln], pos
                pos += ln
            elif wire == 0:
                v, pos = _read_varint(buf, pos)
                yield field, v, pos
            elif wire == 5:
                yield field, buf[pos:pos + 4], pos
                pos += 4
            elif wire == 1:
                yield field, buf[pos:pos + 8], pos
                pos += 8
            else:
                raise ValueError(f"unsupported wire type {wire}")

    out: dict = {}
    # Example{1: Features{1: map<string, Feature>}}
    for f, features_buf, _ in parse_fields(view, 0, len(view)):
        if f != 1:
            continue
        for f2, entry, _ in parse_fields(features_buf, 0, len(features_buf)):
            if f2 != 1:
                continue
            name, value = None, None
            for f3, v, _ in parse_fields(entry, 0, len(entry)):
                if f3 == 1:
                    name = bytes(v).decode()
                elif f3 == 2:
                    # Feature{1: BytesList, 2: FloatList, 3: Int64List}
                    for f4, lst, _ in parse_fields(v, 0, len(v)):
                        if f4 == 1:      # bytes_list{1: repeated bytes}
                            value = [bytes(b) for f5, b, _ in
                                     parse_fields(lst, 0, len(lst))
                                     if f5 == 1]
                        elif f4 == 2:    # float_list{1: packed floats}
                            packed = b"".join(
                                bytes(b) for f5, b, _ in
                                parse_fields(lst, 0, len(lst)) if f5 == 1)
                            value = np.frombuffer(packed, dtype="<f4")
                        elif f4 == 3:    # int64_list{1: varints (packed)}
                            vals = []
                            for f5, b, _ in parse_fields(lst, 0, len(lst)):
                                if f5 != 1:
                                    continue
                                if isinstance(b, int):
                                    vals.append(_signed_int64(b))
                                else:
                                    p = 0
                                    while p < len(b):
                                        x, p = _read_varint(b, p)
                                        vals.append(_signed_int64(x))
                            value = np.asarray(vals, dtype=np.int64)
            if name is not None:
                out[name] = value
    return out


def read_tfrecords(paths) -> Dataset:
    """TFRecord files of tf.train.Example → feature-dict rows
    (reference `ray.data.read_tfrecords`), parsed with a built-in wire
    parser — no tensorflow required."""
    import struct

    files = _expand_paths(paths)

    def read_one(path):
        rows = []
        with _fs.open(path, "rb") as f:
            while True:
                header = f.read(12)
                if len(header) < 12:
                    break
                (length,) = struct.unpack("<Q", header[:8])
                data = f.read(length)
                f.read(4)  # data crc
                rows.append(_parse_tf_example(data))
        return rows

    return Dataset([functools.partial(read_one, f) for f in files])


def read_webdataset(paths) -> Dataset:
    """WebDataset tar shards → one row per sample (reference
    `ray.data.read_webdataset`): files sharing a basename group into a
    dict keyed by extension, e.g. {"__key__", "jpg", "cls", "json"}.
    Pure tarfile — no webdataset dependency; image/json/cls payloads
    decode to arrays/objects, the rest stay bytes."""
    import io as _io
    import json as _json
    import tarfile

    files = _expand_paths(paths)

    def _decode(ext: str, data: bytes):
        if ext in ("json",):
            return _json.loads(data)
        if ext in ("cls", "id", "index"):
            try:
                return int(data.decode().strip())
            except ValueError:
                return data.decode().strip()
        if ext in ("txt", "text"):
            return data.decode()
        if ext in ("jpg", "jpeg", "png", "bmp", "webp"):
            try:
                from PIL import Image

                return np.asarray(Image.open(_io.BytesIO(data)))
            except Exception:
                return data
        if ext == "npy":
            return np.load(_io.BytesIO(data))
        return data

    def read_one(path):
        rows = []
        current_key, sample = None, {}
        with _fs.open(path, "rb") as f:
            with tarfile.open(fileobj=f, mode="r|*") as tar:
                for member in tar:
                    if not member.isfile():
                        continue
                    base, _, ext = member.name.partition(".")
                    if base != current_key:
                        if sample:
                            rows.append(sample)
                        current_key = base
                        sample = {"__key__": base}
                    payload = tar.extractfile(member).read()
                    sample[ext] = _decode(ext.lower(), payload)
        if sample:
            rows.append(sample)
        return rows

    return Dataset([functools.partial(read_one, f) for f in files])


def _tf_feature_bytes(value) -> bytes:
    """Encode one feature as a tf.train.Feature message (wire format)."""
    import struct

    def varint(n: int) -> bytes:
        out = b""
        while True:
            b = n & 0x7F
            n >>= 7
            out += bytes([b | (0x80 if n else 0)])
            if not n:
                return out

    def field(num: int, wire: int, payload: bytes) -> bytes:
        return varint((num << 3) | wire) + payload

    def length_delim(num: int, payload: bytes) -> bytes:
        return field(num, 2, varint(len(payload)) + payload)

    if isinstance(value, bytes):
        inner = length_delim(1, value)          # bytes_list.value
        return length_delim(1, inner)           # Feature.bytes_list
    if isinstance(value, str):
        return _tf_feature_bytes(value.encode())
    arr = np.asarray(value)
    if arr.dtype.kind == "f":
        packed = arr.astype("<f4").tobytes()
        inner = length_delim(1, packed)         # float_list.value packed
        return length_delim(2, inner)           # Feature.float_list
    vals = b"".join(varint(int(v) & ((1 << 64) - 1))
                    for v in arr.reshape(-1))
    inner = length_delim(1, vals)               # int64_list.value packed
    return length_delim(3, inner)               # Feature.int64_list


def _row_to_tf_example(row: dict) -> bytes:
    def varint(n: int) -> bytes:
        out = b""
        while True:
            b = n & 0x7F
            n >>= 7
            out += bytes([b | (0x80 if n else 0)])
            if not n:
                return out

    entries = b""
    for name, value in row.items():
        key = name.encode()
        kv = (bytes([0x0A, len(key)]) + key           # map key (field 1)
              + bytes([0x12]) + varint(len(_tf_feature_bytes(value)))
              + _tf_feature_bytes(value))             # map value (field 2)
        entries += bytes([0x0A]) + varint(len(kv)) + kv
    features = bytes([0x0A]) + varint(len(entries)) + entries
    return features
