"""Dataset creation: range/from_items/from_numpy + file IO connectors.

Parity (core subset) with `python/ray/data/read_api.py`: parquet/csv/json/
text/binary/numpy readers produce one read thunk per file (or per range
shard), executed lazily by the streaming executor.
"""

from __future__ import annotations

import functools
import glob as glob_mod
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.data.dataset import Dataset


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                f for f in glob_mod.glob(os.path.join(p, "**"), recursive=True)
                if os.path.isfile(f) and not os.path.basename(f).startswith(".")))
        elif any(c in p for c in "*?["):
            out.extend(sorted(glob_mod.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    parallelism = max(1, min(parallelism, n or 1))
    bounds = np.linspace(0, n, parallelism + 1, dtype=np.int64)

    def make(lo: int, hi: int):
        return lambda: {"id": np.arange(lo, hi, dtype=np.int64)}

    return Dataset([make(int(lo), int(hi))
                    for lo, hi in zip(bounds[:-1], bounds[1:])])


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    parallelism = max(1, min(parallelism, len(items) or 1))
    shards = np.array_split(np.arange(len(items)), parallelism)

    def make(idx):
        chunk = [items[i] for i in idx]
        return lambda: chunk

    return Dataset([make(idx) for idx in shards if len(idx)])


def from_numpy(arrays: Dict[str, np.ndarray], *, parallelism: int = 8) -> Dataset:
    n = len(next(iter(arrays.values())))
    bounds = np.linspace(0, n, max(1, parallelism) + 1, dtype=np.int64)

    def make(lo, hi):
        chunk = {k: v[lo:hi] for k, v in arrays.items()}
        return lambda: chunk

    return Dataset([make(int(lo), int(hi))
                    for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo])


def from_pandas(df) -> Dataset:
    return from_numpy({c: df[c].to_numpy() for c in df.columns})


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        import pyarrow.parquet as pq

        # arrow IS a block format: no eager numpy conversion — slices
        # stay zero-copy views, consumers convert per-batch
        return pq.read_table(path, columns=columns)

    return Dataset([functools.partial(read_one, f) for f in files])


def read_csv(paths, **csv_kwargs) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        import pandas as pd

        df = pd.read_csv(path, **csv_kwargs)
        return {c: df[c].to_numpy() for c in df.columns}

    return Dataset([functools.partial(read_one, f) for f in files])


def read_json(paths, *, lines: bool = True) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        import json

        rows = []
        with open(path) as f:
            if lines:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
            else:
                data = json.load(f)
                rows = data if isinstance(data, list) else [data]
        return rows

    return Dataset([functools.partial(read_one, f) for f in files])


def read_text(paths) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        with open(path) as f:
            return {"text": np.asarray([ln.rstrip("\n") for ln in f],
                                       dtype=object)}

    return Dataset([functools.partial(read_one, f) for f in files])


def read_binary_files(paths) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        with open(path, "rb") as f:
            return [{"path": path, "bytes": f.read()}]

    return Dataset([functools.partial(read_one, f) for f in files])


def read_numpy(paths) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        arr = np.load(path)
        return {"data": arr}

    return Dataset([functools.partial(read_one, f) for f in files])


IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".gif", ".bmp", ".webp",
                    ".tif", ".tiff")


def read_images(paths, *, size=None, mode: str = "RGB") -> Dataset:
    """Image files → {"image": HWC uint8 array, "path": str} rows
    (reference `ray.data.read_images`). `size=(h, w)` resizes. Directory
    reads skip non-image files (READMEs, labels.csv, ...)."""
    files = [f for f in _expand_paths(paths)
             if f.lower().endswith(IMAGE_EXTENSIONS)]
    if not files:
        raise FileNotFoundError(f"no image files matched {paths}")

    def read_one(path):
        from PIL import Image

        img = Image.open(path).convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        return [{"image": np.asarray(img), "path": path}]

    return Dataset([functools.partial(read_one, f) for f in files])
