"""ray_tpu.data: streaming dataset engine (reference: python/ray/data/,
SURVEY §2.6) — lazy plans, fused per-block tasks, bounded-window streaming."""

from ray_tpu.data.block import Block
from ray_tpu.data.dataset import Dataset, GroupedData
from ray_tpu.data.read_api import (from_arrow, from_huggingface, from_items,
                                   from_numpy, from_pandas, from_torch, range,
                                   read_binary_files, read_csv, read_images,
                                   read_json, read_numpy, read_parquet,
                                   read_sql, read_text, read_tfrecords)

__all__ = [
    "Block", "Dataset", "GroupedData", "range", "from_items", "from_numpy",
    "from_pandas", "read_parquet", "read_csv", "read_json", "read_text",
    "read_binary_files", "read_numpy", "read_images", "read_tfrecords",
    "read_sql", "from_arrow", "from_torch", "from_huggingface",
]
from ray_tpu.data.read_api import read_webdataset  # noqa: E402,F401

__all__.append("read_webdataset")
