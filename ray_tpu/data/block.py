"""Block format + accessor.

Parity with `python/ray/data/block.py` + `_internal/arrow_block.py` in
miniature: a block is either a column dict of numpy arrays (tabular; the
TPU-relevant case — token batches feed jax directly) or a plain list of rows.
The accessor hides the difference for slicing/concat/batching.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Union

import numpy as np

Block = Union[Dict[str, np.ndarray], List[Any]]


def block_len(block: Block) -> int:
    if isinstance(block, dict):
        return len(next(iter(block.values()))) if block else 0
    return len(block)


def block_slice(block: Block, start: int, end: int) -> Block:
    if isinstance(block, dict):
        return {k: v[start:end] for k, v in block.items()}
    return block[start:end]


def block_concat(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if block_len(b) > 0]
    if not blocks:
        return []
    if isinstance(blocks[0], dict):
        keys = blocks[0].keys()
        return {k: np.concatenate([np.asarray(b[k]) for b in blocks])
                for k in keys}
    out: List[Any] = []
    for b in blocks:
        out.extend(b)
    return out


def block_to_batch(block: Block, batch_format: str) -> Any:
    if batch_format in ("numpy", "default"):
        return block
    if batch_format == "pandas":
        import pandas as pd

        if isinstance(block, dict):
            return pd.DataFrame(block)
        return pd.DataFrame({"item": block})
    if batch_format == "pyarrow":
        import pyarrow as pa

        if isinstance(block, dict):
            return pa.table({k: pa.array(v) for k, v in block.items()})
        return pa.table({"item": pa.array(block)})
    raise ValueError(f"unknown batch_format {batch_format!r}")


def batch_to_block(batch: Any) -> Block:
    """Normalize a user-returned batch into a block."""
    if isinstance(batch, (dict, list)):
        if isinstance(batch, dict):
            return {k: np.asarray(v) for k, v in batch.items()}
        return batch
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return {c: batch[c].to_numpy() for c in batch.columns}
    except ImportError:
        pass
    try:
        import pyarrow as pa

        if isinstance(batch, pa.Table):
            return {name: batch.column(name).to_numpy(zero_copy_only=False)
                    for name in batch.column_names}
    except ImportError:
        pass
    raise TypeError(f"unsupported batch type {type(batch)}")


def rows_of(block: Block) -> Iterable[Any]:
    if isinstance(block, dict):
        keys = list(block)
        for i in range(block_len(block)):
            yield {k: block[k][i] for k in keys}
    else:
        yield from block
