"""Block format + accessor.

Parity with `python/ray/data/block.py` + `_internal/arrow_block.py`: a
block is a column dict of numpy arrays (tabular; the TPU-relevant case —
token batches feed jax directly), a `pyarrow.Table` (zero-copy parquet
reads; sliced without copying, converted to numpy only at consumption),
or a plain list of rows. The accessor hides the difference for
slicing/concat/batching; barrier ops (shuffle/sort/join) normalize to
numpy columns first via `to_numpy_columns`.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Union

import numpy as np

Block = Union[Dict[str, np.ndarray], List[Any]]  # | pyarrow.Table


def is_arrow_block(block: Any) -> bool:
    try:
        import pyarrow as pa
    except ImportError:
        return False
    return isinstance(block, pa.Table)


def to_numpy_columns(block: Block) -> Block:
    """Arrow table -> numpy column dict; everything else passes through.
    Barrier ops and batch emission call this — the map/stream hot path
    keeps arrow blocks zero-copy."""
    if is_arrow_block(block):
        return {name: block.column(name).to_numpy(zero_copy_only=False)
                for name in block.column_names}
    return block


def block_nbytes(block: Block) -> int:
    """Approximate in-memory size; drives the streaming executor's
    memory-budget backpressure."""
    if is_arrow_block(block):
        return int(block.nbytes)
    if isinstance(block, dict):
        return int(sum(np.asarray(v).nbytes for v in block.values()))
    return 64 * len(block)  # rows of unknown size: rough per-row guess


def block_len(block: Block) -> int:
    if isinstance(block, dict):
        return len(next(iter(block.values()))) if block else 0
    if is_arrow_block(block):
        return block.num_rows
    return len(block)


def block_slice(block: Block, start: int, end: int) -> Block:
    if isinstance(block, dict):
        return {k: v[start:end] for k, v in block.items()}
    if is_arrow_block(block):
        return block.slice(start, end - start)  # zero-copy view
    return block[start:end]


def block_concat(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if block_len(b) > 0]
    if not blocks:
        return []
    if any(is_arrow_block(b) for b in blocks):
        if all(is_arrow_block(b) for b in blocks):
            import pyarrow as pa

            return pa.concat_tables(blocks)
        # mixed arrow/numpy: normalize each block ONCE, not per column
        blocks = [to_numpy_columns(b) for b in blocks]
    if isinstance(blocks[0], dict):
        keys = blocks[0].keys()
        return {k: np.concatenate([np.asarray(b[k]) for b in blocks])
                for k in keys}
    out: List[Any] = []
    for b in blocks:
        out.extend(b)
    return out


def block_to_batch(block: Block, batch_format: str) -> Any:
    if batch_format in ("numpy", "default"):
        return to_numpy_columns(block)
    if batch_format == "pandas":
        import pandas as pd

        if is_arrow_block(block):
            return block.to_pandas()
        if isinstance(block, dict):
            return pd.DataFrame(block)
        return pd.DataFrame({"item": block})
    if batch_format == "pyarrow":
        import pyarrow as pa

        if is_arrow_block(block):
            return block
        if isinstance(block, dict):
            return pa.table({k: pa.array(np.asarray(v))
                             for k, v in block.items()})
        return pa.table({"item": pa.array(block)})
    raise ValueError(f"unknown batch_format {batch_format!r}")


def batch_to_block(batch: Any) -> Block:
    """Normalize a user-returned batch into a block."""
    if isinstance(batch, (dict, list)):
        if isinstance(batch, dict):
            return {k: np.asarray(v) for k, v in batch.items()}
        return batch
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return {c: batch[c].to_numpy() for c in batch.columns}
    except ImportError:
        pass
    try:
        import pyarrow as pa

        if isinstance(batch, pa.Table):
            return batch  # arrow is a first-class block format
    except ImportError:
        pass
    raise TypeError(f"unsupported batch type {type(batch)}")


def rows_of(block: Block) -> Iterable[Any]:
    if is_arrow_block(block):
        yield from block.to_pylist()
        return
    if isinstance(block, dict):
        keys = list(block)
        for i in range(block_len(block)):
            yield {k: block[k][i] for k in keys}
    else:
        yield from block
