"""Data preprocessors: fit statistics on a Dataset, transform lazily.

Parity (core family) with `python/ray/data/preprocessors/`
(StandardScaler, MinMaxScaler, LabelEncoder, OneHotEncoder,
Concatenator): `fit` streams the dataset once accumulating statistics
(driver holds only the accumulators, never the data), `transform`
appends a lazy map_batches so the work runs in the cluster and composes
with the operator-graph executor. `transform_batch` applies the fitted
stats to a single in-memory batch (the serving-time path).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Preprocessor:
    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        if not self._fitted:
            raise RuntimeError(f"{type(self).__name__} is not fitted")
        return ds.map_batches(self.transform_batch)

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def _fit(self, ds) -> None:
        raise NotImplementedError

    def transform_batch(self, batch: Dict[str, np.ndarray]
                        ) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (Welford streaming fit)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        acc = {c: [0.0, None, None] for c in self.columns}  # n, mean, m2
        for batch in ds.iter_batches(batch_size=4096):
            for c in self.columns:
                b = np.asarray(batch[c], np.float64)
                n = len(b)
                cnt, mean, m2 = acc[c]
                if mean is None:
                    acc[c] = [n, b.mean(0), b.var(0) * n]
                else:
                    delta = b.mean(0) - mean
                    tot = cnt + n
                    acc[c] = [tot, mean + delta * n / tot,
                              m2 + b.var(0) * n
                              + delta ** 2 * cnt * n / tot]
        self.stats_ = {c: (acc[c][1], np.sqrt(acc[c][2] / max(acc[c][0], 1)))
                       for c in self.columns}

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats_[c]
            out[c] = ((np.asarray(batch[c], np.float64) - mean)
                      / np.where(std == 0, 1.0, std)).astype(np.float32)
        return out


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        lo = {c: None for c in self.columns}
        hi = {c: None for c in self.columns}
        for batch in ds.iter_batches(batch_size=4096):
            for c in self.columns:
                b = np.asarray(batch[c], np.float64)
                bmin, bmax = b.min(0), b.max(0)
                lo[c] = bmin if lo[c] is None else np.minimum(lo[c], bmin)
                hi[c] = bmax if hi[c] is None else np.maximum(hi[c], bmax)
        self.stats_ = {c: (lo[c], hi[c]) for c in self.columns}

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            lo, hi = self.stats_[c]
            rng = np.where(hi - lo == 0, 1.0, hi - lo)
            out[c] = ((np.asarray(batch[c], np.float64) - lo)
                      / rng).astype(np.float32)
        return out


class LabelEncoder(Preprocessor):
    """Categorical column -> dense int codes (sorted vocabulary)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Optional[np.ndarray] = None

    def _fit(self, ds) -> None:
        seen = set()
        for batch in ds.iter_batches(batch_size=4096):
            seen.update(np.asarray(batch[self.label_column]).tolist())
        self.classes_ = np.asarray(sorted(seen))

    def transform_batch(self, batch):
        out = dict(batch)
        idx = {v: i for i, v in enumerate(self.classes_.tolist())}
        out[self.label_column] = np.asarray(
            [idx[v] for v in np.asarray(batch[self.label_column]).tolist()],
            np.int64)
        return out


class OneHotEncoder(Preprocessor):
    """Categorical columns -> `<col>_<value>` 0/1 columns."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.categories_: Dict[str, np.ndarray] = {}

    def _fit(self, ds) -> None:
        seen: Dict[str, set] = {c: set() for c in self.columns}
        for batch in ds.iter_batches(batch_size=4096):
            for c in self.columns:
                seen[c].update(np.asarray(batch[c]).tolist())
        self.categories_ = {c: np.asarray(sorted(v))
                            for c, v in seen.items()}

    def transform_batch(self, batch):
        out = {k: v for k, v in batch.items() if k not in self.columns}
        for c in self.columns:
            vals = np.asarray(batch[c])
            for cat in self.categories_[c].tolist():
                out[f"{c}_{cat}"] = (vals == cat).astype(np.int8)
        return out


class Concatenator(Preprocessor):
    """Merge columns into one float matrix column (training ingest:
    feature columns -> a single model-input array)."""

    def __init__(self, columns: Optional[List[str]] = None,
                 output_column_name: str = "concat_out",
                 exclude: Optional[List[str]] = None):
        self.columns = columns
        self.output_column_name = output_column_name
        self.exclude = set(exclude or [])
        self._fitted = True   # stateless

    def _fit(self, ds) -> None:
        pass

    def transform_batch(self, batch):
        cols = (self.columns if self.columns is not None
                else [c for c in batch if c not in self.exclude])
        parts = []
        for c in cols:
            a = np.asarray(batch[c], np.float32)
            parts.append(a[:, None] if a.ndim == 1 else a)
        out = {k: v for k, v in batch.items()
               if k not in cols}
        out[self.output_column_name] = np.concatenate(parts, axis=1)
        return out


class Chain(Preprocessor):
    """Apply preprocessors in sequence (reference Chain)."""

    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    def fit(self, ds) -> "Chain":
        # each stage fits on the PREVIOUS stage's output (lazy, still
        # cluster-executed per fit pass)
        for i, p in enumerate(self.preprocessors):
            p.fit(ds)
            ds = p.transform(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch
