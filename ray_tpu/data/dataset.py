"""Dataset: lazy logical plan + streaming execution over the task runtime.

Parity (miniature) with `python/ray/data/dataset.py` +
`_internal/execution/streaming_executor.py:61`: transformations build a lazy
plan; execution fuses consecutive per-block ops into one task per block and
streams blocks through with bounded in-flight tasks (backpressure = window
size). Barrier ops (repartition/shuffle/sort/groupby) materialize.

TPU-first notes: blocks are numpy column dicts that feed `jax.device_put`
directly; `iter_batches` re-batches across block boundaries so a fixed
training batch shape (static XLA shapes!) is always delivered.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

import numpy as np

from ray_tpu.data.block import (Block, batch_to_block, block_concat,
                                block_len, block_nbytes, block_slice,
                                block_to_batch, rows_of, to_numpy_columns)

DEFAULT_WINDOW = 8  # initial in-flight block tasks (adapts to a byte budget)
# streaming memory budget (reference resource_budget_backpressure_policy):
# the in-flight window adapts so (avg block bytes x window) stays under it
from ray_tpu.core import config as _config


def DATA_MEMORY_BUDGET() -> int:   # call-time: env/set() changes apply
    return _config.get("data_memory_budget_bytes")


MIN_WINDOW, MAX_WINDOW = 2, 64


# ----------------------------------------------------------- logical plan
@dataclasses.dataclass
class _Op:
    kind: str                  # "map_batches" | "map" | "filter" | "flat_map"
    fn: Callable               # | "repartition" | "shuffle" | "sort" | "limit"
    arg: Any = None
    batch_format: str = "numpy"
    # actor-pool compute (reference actor_pool_map_operator): fn is a class;
    # `concurrency` actors each hold one instance
    concurrency: int = 0


def _apply_op(block: Block, op: _Op) -> Block:
    if op.kind == "map_batches":
        batch = block_to_batch(block, op.batch_format)
        fn = op.fn() if isinstance(op.fn, type) else op.fn
        return batch_to_block(fn(batch))
    if op.kind == "map":
        return _rows_to_block([op.fn(r) for r in rows_of(block)])
    if op.kind == "filter":
        return _rows_to_block([r for r in rows_of(block) if op.fn(r)])
    if op.kind == "flat_map":
        out = []
        for r in rows_of(block):
            out.extend(op.fn(r))
        return _rows_to_block(out)
    raise ValueError(f"not a per-block op: {op.kind}")


def _zip_blocks(lb: Block, rb: Block) -> Block:
    lb, rb = to_numpy_columns(lb), to_numpy_columns(rb)

    def to_cols(b, side):
        if not isinstance(b, dict):
            b = _rows_to_block(list(b))
        if not isinstance(b, dict):
            raise ValueError(
                f"zip() requires tabular (column) data; {side} side has "
                "non-dict rows")
        return b

    merged = dict(to_cols(lb, "left"))
    for k, v in to_cols(rb, "right").items():
        merged[k if k not in merged else f"{k}_1"] = v
    return merged


def _join_blocks(lb: Block, rb: Block, on: str, how: str) -> Block:
    """Hash-join two co-partitioned blocks into row dicts."""
    lb, rb = to_numpy_columns(lb), to_numpy_columns(rb)
    import collections

    lrows = list(rows_of(lb))
    rrows = list(rows_of(rb))
    rindex: Dict[Any, List[dict]] = collections.defaultdict(list)
    for r in rrows:
        rindex[r[on]].append(r)
    lkeys = {r[on] for r in lrows}
    out: List[dict] = []
    lcols = set().union(*(r.keys() for r in lrows)) if lrows else set()
    rcols = set().union(*(r.keys() for r in rrows)) if rrows else set()

    def merge(l, r):
        row = dict(l or {k: None for k in lcols})
        for k, v in (r or {k: None for k in rcols}).items():
            if k == on:
                row[on] = row.get(on) if row.get(on) is not None else v
            else:
                row[k if k not in lcols or k == on else f"{k}_1"] = v
        return row

    for l in lrows:
        matches = rindex.get(l[on], [])
        if matches:
            out.extend(merge(l, r) for r in matches)
        elif how in ("left", "outer"):
            out.append(merge(l, None))
    if how in ("right", "outer"):
        for r in rrows:
            if r[on] not in lkeys:
                out.append(merge(None, r))
    return out


def _rows_to_block(items: List[Any]) -> Block:
    if items and isinstance(items[0], dict) and all(
            isinstance(r, dict) for r in items):
        keys = items[0].keys()
        if all(r.keys() == keys for r in items):
            return {k: np.asarray([r[k] for r in items]) for k in keys}
    return items


def _exec_chain(source, ops: List[_Op]) -> Block:
    block = source() if callable(source) else source
    for op in ops:
        block = _apply_op(block, op)
    return block


def _make_block_actor():
    import ray_tpu

    @ray_tpu.remote
    class _BlockActorImpl:
        """One instance of a callable-class UDF; blocks stream through it
        (reference actor_pool_map_operator worker)."""

        def __init__(self, fn_cls):
            self.fn = fn_cls() if isinstance(fn_cls, type) else fn_cls

        def apply(self, block, batch_format):
            return batch_to_block(self.fn(block_to_batch(block, batch_format)))

    return _BlockActorImpl


class _BlockActorProxy:
    _cls = None

    @classmethod
    def remote(cls, fn):
        if cls._cls is None:
            cls._cls = _make_block_actor()
        return cls._cls.remote(fn)


_BlockActor = _BlockActorProxy


class Dataset:
    """Lazy, immutable; every transform returns a new Dataset."""

    def __init__(self, partitions: List[Any], ops: Optional[List[_Op]] = None,
                 parallelism: Optional[int] = None):
        # partitions: read thunks (callables) or ObjectRefs of blocks
        self._partitions = partitions
        self._ops = ops or []
        self._parallelism = parallelism

    # ----------------------------------------------------------- transforms
    def _with_op(self, op: _Op) -> "Dataset":
        return Dataset(self._partitions, self._ops + [op], self._parallelism)

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    concurrency: Optional[int] = None,
                    compute: Optional[str] = None, **_ignored) -> "Dataset":
        """`fn` may be a callable class (reference semantics): it is then
        instantiated once per pool actor and blocks stream through the pool."""
        use_actors = (isinstance(fn, type) or compute == "actors"
                      or (concurrency or 0) > 0)
        return self._with_op(_Op("map_batches", fn, batch_format=batch_format,
                                 concurrency=(concurrency or 2) if use_actors
                                 else 0))

    def map(self, fn: Callable) -> "Dataset":
        return self._with_op(_Op("map", fn))

    def filter(self, fn: Callable) -> "Dataset":
        return self._with_op(_Op("filter", fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with_op(_Op("flat_map", fn))

    def union(self, *others: "Dataset") -> "Dataset":
        blocks = self._barrier_blocks()
        for o in others:
            blocks.extend(o._barrier_blocks())
        return Dataset(blocks, [], self._parallelism)

    def limit(self, n: int) -> "Dataset":
        out: List[Block] = []
        total = 0
        for block in self._stream_blocks():
            take = min(n - total, block_len(block))
            if take > 0:
                out.append(block_slice(block, 0, take))
                total += take
            if total >= n:
                break
        return Dataset(out, [], self._parallelism)

    def _shuffled(self, P: int, mode: str, **kw) -> "Dataset":
        """Two-stage distributed shuffle; blocks never touch the driver
        (ray_tpu.data.shuffle). Falls back to local execution when no
        cluster is up."""
        import ray_tpu
        from ray_tpu.data import shuffle as shf

        if not ray_tpu.is_initialized():
            # local fallback: same algorithm, thunks instead of tasks
            base = kw.get("seed")
            parts = [shf._map_partition(p, self._ops, P, mode,
                                        kw.get("key"),
                                        None if base is None
                                        else base + 7919 * i,
                                        kw.get("boundaries"))
                     for i, p in enumerate(self._partitions)]
            reduce_fn = kw.get("reduce_fn") or shf._reduce_concat
            extra = kw.get("reduce_extra_args", ())
            blocks = []
            for i in range(P):
                cols = [(pp[i] if P > 1 else pp) for pp in parts]
                blocks.append(reduce_fn(*extra, *cols))
            return Dataset(blocks, [], self._parallelism)
        refs = shf.shuffle_refs(self._partitions, self._ops, P, mode, **kw)
        return Dataset(refs, [], self._parallelism)

    def repartition(self, num_blocks: int) -> "Dataset":
        """Order-preserving (reference semantics): block i holds a
        contiguous range of the global row order."""
        from ray_tpu.data import shuffle as shf

        lens = shf.block_lens(self._partitions, self._ops)
        n = sum(lens)
        sizes = [n // num_blocks + (1 if i < n % num_blocks else 0)
                 for i in range(num_blocks)]
        return self._reshard_to_sizes(sizes, lens=lens)

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        from ray_tpu.data import shuffle as shf

        P = max(len(self._partitions), 1)
        return self._shuffled(P, "random", seed=seed,
                              reduce_fn=shf._reduce_shuffled,
                              reduce_extra_args=(
                                  np.random.randint(1 << 31)
                                  if seed is None else seed + 13,))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed sample sort: range-partition on sampled boundaries,
        then sort each partition (partitions emerge globally ordered)."""
        import ray_tpu
        from ray_tpu.data import shuffle as shf

        P = max(len(self._partitions), 1)
        if ray_tpu.is_initialized() and P > 1:
            bounds = shf.sample_boundaries(self._partitions, self._ops, key, P)
        else:
            allv = []
            for b in Dataset(list(self._partitions), list(self._ops))._stream_blocks():
                if isinstance(b, dict):
                    allv.append(np.asarray(b[key]))
                else:
                    allv.append(np.asarray([r[key] for r in rows_of(b)]))
            cat = np.sort(np.concatenate(allv)) if allv else np.zeros(0)
            qs = np.linspace(0, max(len(cat) - 1, 0), P + 1)[1:-1].astype(int)
            bounds = cat[qs] if len(cat) else np.zeros(P - 1)
        ds = self._shuffled(P, "range", key=key, boundaries=bounds,
                            reduce_fn=shf._reduce_sorted,
                            reduce_extra_args=(key, descending))
        if descending:
            ds._partitions = list(reversed(ds._partitions))
        return ds

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def join(self, other: "Dataset", on: str, how: str = "inner",
             num_partitions: Optional[int] = None) -> "Dataset":
        """Distributed hash join (reference `Dataset.join` /
        `_internal/execution/operators/join.py`): both sides hash-partition
        on `on`; co-partitions join in reduce tasks."""
        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(f"unsupported how={how!r}")
        import ray_tpu

        P = num_partitions or max(len(self._partitions),
                                  len(other._partitions), 1)
        left = self._shuffled(P, "hash", key=on)
        right = other._shuffled(P, "hash", key=on)

        def join_parts(lb, rb):
            return _join_blocks(lb, rb, on, how)

        if ray_tpu.is_initialized():
            task = ray_tpu.remote(join_parts).options(
                name="data_join", lineage=True, data_stage=True)
            refs = [task.remote(l, r) for l, r in
                    zip(left._partitions, right._partitions)]
            return Dataset(refs, [], self._parallelism)
        return Dataset([join_parts(l() if callable(l) else l,
                                   r() if callable(r) else r)
                        for l, r in zip(left._partitions, right._partitions)],
                       [], self._parallelism)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of equal-length tabular datasets (reference
        `Dataset.zip`); the right side is resharded once to the left's
        block sizes, then blocks merge pairwise in tasks. Each side's op
        chain executes exactly once; only row counts reach the driver."""
        import ray_tpu
        from ray_tpu.data import shuffle as shf

        left = self.materialize()
        lsizes = shf.block_lens(left._partitions)
        rlens = shf.block_lens(other._partitions, other._ops)
        if sum(rlens) != sum(lsizes):
            raise ValueError("zip() requires equal row counts")
        right = other._reshard_to_sizes(lsizes, lens=rlens)

        if ray_tpu.is_initialized():
            task = ray_tpu.remote(_zip_blocks)
            return Dataset([task.remote(l, r) for l, r in
                            zip(left._partitions, right._partitions)], [],
                           self._parallelism)
        rblocks = list(Dataset(list(right._partitions), [])._stream_blocks())
        return Dataset([_zip_blocks(l() if callable(l) else l, r)
                        for l, r in zip(left._partitions, rblocks)], [],
                       self._parallelism)

    def _reshard_to_sizes(self, sizes: List[int],
                          lens: Optional[List[int]] = None) -> "Dataset":
        """Reshard so block i has exactly sizes[i] rows, preserving global
        row order (zip alignment + order-preserving repartition)."""
        from ray_tpu.data import shuffle as shf

        lens = lens if lens is not None else shf.block_lens(
            self._partitions, self._ops)
        if sum(lens) != sum(sizes):
            raise ValueError("reshard requires equal row counts")
        bounds = np.cumsum(sizes)[:-1]  # searchsorted(.., 'right') boundaries
        offsets = np.concatenate([[0], np.cumsum(lens)[:-1]])
        import ray_tpu

        P = len(sizes)
        if ray_tpu.is_initialized():
            map_task = ray_tpu.remote(shf._map_partition).options(
                num_returns=P, name="data_reshard_map", data_stage=True)
            reducer = ray_tpu.remote(shf._reduce_concat).options(
                name="data_reshard_reduce", lineage=True, data_stage=True)
            map_out = []
            for src, off in zip(self._partitions, offsets):
                refs = map_task.remote(src, self._ops, P, "offset",
                                       None, int(off), bounds)
                map_out.append([refs] if P == 1 else refs)
            return Dataset([reducer.remote(*[m[p] for m in map_out])
                            for p in range(P)], [], self._parallelism)
        parts = [shf._map_partition(src, self._ops, P, "offset", None,
                                    int(off), bounds)
                 for src, off in zip(self._partitions, offsets)]
        return Dataset([shf._reduce_concat(*[(pp[p] if P > 1 else pp)
                                             for pp in parts])
                        for p in range(P)], [], self._parallelism)

    # ------------------------------------------------------------ execution
    def _segments(self):
        """Split the op chain at actor-pool ops: [task-ops] → actor-op →
        [task-ops] … (reference: TaskPoolMapOperator vs ActorPoolMapOperator
        stages of one streaming topology)."""
        segs: List[tuple] = []   # ("tasks", ops) | ("actor", op)
        cur: List[_Op] = []
        for op in self._ops:
            if op.concurrency:
                segs.append(("tasks", cur))
                segs.append(("actor", op))
                cur = []
            else:
                cur.append(op)
        segs.append(("tasks", cur))
        return segs

    def _stream_blocks(self) -> Iterator[Block]:
        """The streaming executor: fused per-block tasks (actor-pool stages
        pipelined between them), bounded in-flight window."""
        import time as _time

        import ray_tpu

        if not self._partitions:
            return
        t0 = _time.time()
        nrows = 0
        use_tasks = ray_tpu.is_initialized() and (
            len(self._partitions) > 1 or self._ops)
        if not use_tasks:
            from ray_tpu.core.object_ref import ObjectRef

            for p in self._partitions:
                block = p() if callable(p) else p
                if isinstance(block, ObjectRef):
                    # a single-partition barrier output (e.g. sort of a
                    # 1-file dataset) is an ObjectRef even on this path
                    block = ray_tpu.get(block)
                for op in self._ops:
                    block = _apply_op(block, op)
                nrows += block_len(block)
                yield block
            self._record_stats(len(self._partitions), nrows, _time.time() - t0)
            return

        from ray_tpu.data.executor import (ActorStage, StreamingExecutor,
                                           TaskStage)

        # physical plan: fuse adjacent task ops into one TaskStage, one
        # ActorStage per callable-class UDF (operator-graph Topology,
        # reference streaming_executor.py:61)
        stages: List[Any] = []
        for i, (kind, seg) in enumerate(self._segments()):
            if kind == "tasks":
                if seg or i == 0:
                    stages.append(TaskStage(seg))
            else:
                stages.append(ActorStage(seg))

        window = self._parallelism or DEFAULT_WINDOW
        # adaptive backpressure: unless the caller fixed parallelism, size
        # the input window by the byte budget as completed-block sizes
        # come in — a fixed window of 8 is 8x too much memory for GB
        # blocks and 8x too little parallelism for KB blocks
        adapt = self._parallelism is None
        state = {"window": window, "bytes": 0, "blocks": 0}

        def input_window() -> int:
            if adapt and state["blocks"]:
                avg = max(state["bytes"] // state["blocks"], 1)
                state["window"] = min(MAX_WINDOW, max(
                    MIN_WINDOW, int(DATA_MEMORY_BUDGET() // avg)))
            self._last_window = state["window"]  # introspection
            return state["window"]

        executor = StreamingExecutor(stages, list(self._partitions),
                                     input_window)
        self._last_executor = executor   # per-op stats for stats()/tests
        emitted = 0
        results: Dict[int, Any] = {}
        try:
            for idx, ref in executor.run():
                block = ray_tpu.get(ref)
                state["bytes"] += block_nbytes(block)
                state["blocks"] += 1
                results[idx] = block
                # the partition's whole chain is consumed: retire its
                # lineage entries so intermediate blocks evict now (a
                # long pipeline's store footprint stays bounded by the
                # window, not the lineage cap)
                executor.release_partition(idx, final_ref=ref)
                # emit in order (deterministic, like ordered execution)
                while emitted in results:
                    block = results.pop(emitted)
                    nrows += block_len(block)
                    yield block
                    emitted += 1
        finally:
            # executor.run's finally kills pool actors on GeneratorExit
            # (limit()/take() abandoning the stream must not leak them)
            executor.close()
            self._record_stats(len(self._partitions), nrows,
                               _time.time() - t0)

    def _record_stats(self, nblocks: int, nrows: int, wall: float) -> None:
        self._last_stats = {"num_blocks": nblocks, "num_rows": nrows,
                            "wall_time_s": wall}

    def stats(self) -> str:
        """Execution stats of the last run (reference `Dataset.stats()`),
        including per-operator rows when the operator-graph executor ran."""
        st = getattr(self, "_last_stats", None)
        if st is None:
            return "Dataset not executed yet"
        out = (f"{st['num_blocks']} blocks, {st['num_rows']} rows in "
               f"{st['wall_time_s']:.3f}s "
               f"({st['num_rows'] / max(st['wall_time_s'], 1e-9):.0f} rows/s)")
        ex = getattr(self, "_last_executor", None)
        if ex is not None:
            for s in ex.per_op_stats():
                out += f"\n  {s.summary()}"
        return out

    def explain(self) -> str:
        """Logical op chain → physical stage plan (reference
        `ExecutionPlan`/logical-plan repr): adjacent per-block ops fuse
        into one task stage; callable-class UDFs become actor stages."""
        logical = " -> ".join(["Read"] + [o.kind for o in self._ops])
        phys = []
        for i, (kind, seg) in enumerate(self._segments()):
            if kind == "tasks":
                if seg or i == 0:
                    phys.append("TaskStage[" +
                                (",".join(o.kind for o in seg) or "read") +
                                "]")
            else:
                phys.append(f"ActorStage[{seg.kind} x{seg.concurrency}]")
        return f"logical: {logical}\nphysical: {' -> '.join(phys)}"

    def _barrier_blocks(self) -> List[Block]:
        return list(self._stream_blocks())

    # ----------------------------------------------------------- consumers
    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        carry: Optional[Block] = None
        for block in self._stream_blocks():
            if carry is not None:
                block = block_concat([carry, block])
                carry = None
            off = 0
            n = block_len(block)
            while n - off >= batch_size:
                yield block_to_batch(block_slice(block, off, off + batch_size),
                                     batch_format)
                off += batch_size
            if off < n:
                carry = block_slice(block, off, n)
        if carry is not None and not drop_last:
            yield block_to_batch(carry, batch_format)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False,
                           dtypes=None, device=None) -> Iterator[Any]:
        """iter_batches with torch-tensor conversion (reference
        `Dataset.iter_torch_batches`): column dicts become dicts of
        tensors, optionally cast/moved."""
        import torch

        def to_t(v):
            t = torch.as_tensor(np.ascontiguousarray(v))
            if dtypes is not None:
                t = t.to(dtypes)
            if device is not None:
                t = t.to(device)
            return t

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            if isinstance(batch, dict):
                yield {k: to_t(v) for k, v in batch.items()}
            else:
                yield to_t(np.asarray(batch))

    def iter_rows(self) -> Iterator[Any]:
        for block in self._stream_blocks():
            yield from rows_of(block)

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(block_len(b) for b in self._stream_blocks())

    def schema(self) -> Optional[List[str]]:
        from ray_tpu.data.block import is_arrow_block

        for block in self._stream_blocks():
            if is_arrow_block(block):
                return list(block.column_names)
            if isinstance(block, dict):
                return list(block)
            return None
        return None

    def materialize(self) -> "Dataset":
        return Dataset(self._barrier_blocks(), [], self._parallelism)

    def num_blocks(self) -> int:
        return len(self._partitions)

    # --------------------------------------------------------------- splits
    def split(self, n: int) -> List["Dataset"]:
        """Shard by partition round-robin (train ingest: one shard per
        worker; reference streaming_split)."""
        shards: List[List[Any]] = [[] for _ in range(n)]
        for i, p in enumerate(self._partitions):
            shards[i % n].append(p)
        return [Dataset(s, list(self._ops), self._parallelism) for s in shards]

    streaming_split = split

    # -------------------------------------------------------------- writers
    def write_parquet(self, path: str) -> None:
        import pyarrow.parquet as pq

        from ray_tpu.utils import fs as _fs

        _fs.makedirs(path)
        for i, block in enumerate(self._stream_blocks()):
            table = block_to_batch(block, "pyarrow")
            with _fs.open(_fs.join(path, f"part-{i:05d}.parquet"),
                          "wb") as f:
                pq.write_table(table, f)

    def write_csv(self, path: str) -> None:
        """One CSV file per block (reference `Dataset.write_csv`)."""
        import csv

        from ray_tpu.utils import fs as _fs

        _fs.makedirs(path)
        for i, block in enumerate(self._stream_blocks()):
            cols = to_numpy_columns(block)
            out = _fs.join(path, f"part-{i:05d}.csv")
            with _fs.open(out, "w", newline="") as f:
                if isinstance(cols, dict):
                    w = csv.writer(f)
                    keys = list(cols)
                    w.writerow(keys)
                    for row in zip(*(cols[k] for k in keys)):
                        w.writerow(row)
                elif cols and all(isinstance(r, dict) for r in cols):
                    # row blocks of dicts get REAL columns, not reprs
                    keys = sorted({k for r in cols for k in r})
                    w = csv.DictWriter(f, fieldnames=keys)
                    w.writeheader()
                    w.writerows(cols)
                else:
                    w = csv.writer(f)
                    w.writerow(["item"])
                    for r in cols:
                        w.writerow([r])

    def write_json(self, path: str) -> None:
        """One JSONL file per block (reference `Dataset.write_json`)."""
        import json as _json

        from ray_tpu.utils import fs as _fs

        _fs.makedirs(path)

        def _py(v):
            if isinstance(v, np.generic):
                return v.item()
            if isinstance(v, np.ndarray):
                return v.tolist()  # json-parseable, not a numpy repr
            return v

        for i, block in enumerate(self._stream_blocks()):
            out = _fs.join(path, f"part-{i:05d}.jsonl")
            with _fs.open(out, "w") as f:
                for row in rows_of(block):
                    if isinstance(row, dict):
                        row = {k: _py(v) for k, v in row.items()}
                    else:
                        row = {"item": _py(row)}
                    f.write(_json.dumps(row, default=str) + "\n")

    def __repr__(self):
        return (f"Dataset(num_blocks={len(self._partitions)}, "
                f"ops={[o.kind for o in self._ops]})")


def _block_groups(block: Block, key: str) -> Dict[Any, Block]:
    """Split one (already key-co-partitioned) block into per-key blocks."""
    import collections

    groups: Dict[Any, List[Any]] = collections.defaultdict(list)
    for row in rows_of(block):
        groups[row[key]].append(row)
    return {k: _rows_to_block(v) for k, v in sorted(groups.items(),
                                                    key=lambda kv: str(kv[0]))}


def _agg_partition(key, specs, block) -> Block:
    """Reduce-stage groupby: aggregate every key group in this hash
    partition. specs = [(col, op_name, out_name)] with op in
    count/sum/mean/min/max/std."""
    fns = {"sum": np.sum, "mean": np.mean, "min": np.min, "max": np.max,
           "std": np.std}
    rows = []
    for k, b in _block_groups(block, key).items():
        row = {key: k}
        for col, op, name in specs:
            if op == "count":
                row[name] = block_len(b)
            else:
                row[name] = fns[op](np.asarray(b[col]))
        rows.append(row)
    return _rows_to_block(rows)


def _map_groups_partition(key, fn, block) -> Block:
    outs = [batch_to_block(fn(block_to_batch(b, "numpy")))
            for _, b in _block_groups(block, key).items()]
    return block_concat(outs) if outs else []


class GroupedData:
    """Distributed groupby: hash-shuffle by key, then per-partition
    aggregation tasks (reference `hash_aggregate` operator) — each key's
    rows land in exactly one partition, so partial results are final."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg_ds(self, specs) -> Dataset:
        import functools

        P = max(min(len(self._ds._partitions), DEFAULT_WINDOW), 1)
        shuffled = self._ds._shuffled(P, "hash", key=self._key)
        return shuffled._with_op(_Op(
            "map_batches",
            functools.partial(_agg_partition_batch, self._key, specs)))

    def aggregate(self, *specs) -> Dataset:
        """specs: (col, op) or (col, op, out_name) tuples."""
        return self._agg_ds([(c, op, rest[0] if rest else f"{op}({c})")
                             for c, op, *rest in specs])

    def count(self) -> Dataset:
        return self._agg_ds([(None, "count", "count")])

    def sum(self, col: str) -> Dataset:
        return self._agg_ds([(col, "sum", f"sum({col})")])

    def mean(self, col: str) -> Dataset:
        return self._agg_ds([(col, "mean", f"mean({col})")])

    def min(self, col: str) -> Dataset:
        return self._agg_ds([(col, "min", f"min({col})")])

    def max(self, col: str) -> Dataset:
        return self._agg_ds([(col, "max", f"max({col})")])

    def std(self, col: str) -> Dataset:
        return self._agg_ds([(col, "std", f"std({col})")])

    def map_groups(self, fn: Callable) -> Dataset:
        import functools

        P = max(min(len(self._ds._partitions), DEFAULT_WINDOW), 1)
        shuffled = self._ds._shuffled(P, "hash", key=self._key)
        return shuffled._with_op(_Op(
            "map_batches",
            functools.partial(_map_groups_partition_batch, self._key, fn)))


def _agg_partition_batch(key, specs, batch):
    return block_to_batch(_agg_partition(key, specs, batch_to_block(batch)),
                          "numpy")


def _map_groups_partition_batch(key, fn, batch):
    return block_to_batch(_map_groups_partition(key, fn,
                                                batch_to_block(batch)),
                          "numpy")


# ------------------------------------------------------------- tfrecords IO
def _crc32c(data: bytes) -> int:
    """Software CRC-32C (Castagnoli) — TFRecord framing checksums."""
    global _CRC32C_TABLE
    try:
        table = _CRC32C_TABLE
    except NameError:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


def _write_tfrecords(self, path: str) -> None:
    """One TFRecord file of tf.train.Example per block (reference
    `Dataset.write_tfrecords`), rows encoded with the built-in protobuf
    wire writer — no tensorflow required; framing carries real masked
    CRC-32C so TF readers accept the files."""
    import struct

    from ray_tpu.data.read_api import _row_to_tf_example
    from ray_tpu.utils import fs as _fs

    _fs.makedirs(path)
    for i, block in enumerate(self._stream_blocks()):
        out = _fs.join(path, f"part-{i:05d}.tfrecords")
        with _fs.open(out, "wb") as f:
            for row in rows_of(block):
                if not isinstance(row, dict):
                    row = {"item": row}
                data = _row_to_tf_example(row)
                header = struct.pack("<Q", len(data))
                f.write(header)
                f.write(struct.pack("<I", _masked_crc(header)))
                f.write(data)
                f.write(struct.pack("<I", _masked_crc(data)))


Dataset.write_tfrecords = _write_tfrecords
