"""Dataset: lazy logical plan + streaming execution over the task runtime.

Parity (miniature) with `python/ray/data/dataset.py` +
`_internal/execution/streaming_executor.py:61`: transformations build a lazy
plan; execution fuses consecutive per-block ops into one task per block and
streams blocks through with bounded in-flight tasks (backpressure = window
size). Barrier ops (repartition/shuffle/sort/groupby) materialize.

TPU-first notes: blocks are numpy column dicts that feed `jax.device_put`
directly; `iter_batches` re-batches across block boundaries so a fixed
training batch shape (static XLA shapes!) is always delivered.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

import numpy as np

from ray_tpu.data.block import (Block, batch_to_block, block_concat,
                                block_len, block_slice, block_to_batch,
                                rows_of)

DEFAULT_WINDOW = 8  # in-flight block tasks (concurrency cap backpressure)


# ----------------------------------------------------------- logical plan
@dataclasses.dataclass
class _Op:
    kind: str                  # "map_batches" | "map" | "filter" | "flat_map"
    fn: Callable               # | "repartition" | "shuffle" | "sort" | "limit"
    arg: Any = None
    batch_format: str = "numpy"


def _apply_op(block: Block, op: _Op) -> Block:
    if op.kind == "map_batches":
        batch = block_to_batch(block, op.batch_format)
        return batch_to_block(op.fn(batch))
    if op.kind == "map":
        return _rows_to_block([op.fn(r) for r in rows_of(block)])
    if op.kind == "filter":
        return _rows_to_block([r for r in rows_of(block) if op.fn(r)])
    if op.kind == "flat_map":
        out = []
        for r in rows_of(block):
            out.extend(op.fn(r))
        return _rows_to_block(out)
    raise ValueError(f"not a per-block op: {op.kind}")


def _rows_to_block(items: List[Any]) -> Block:
    if items and isinstance(items[0], dict) and all(
            isinstance(r, dict) for r in items):
        keys = items[0].keys()
        if all(r.keys() == keys for r in items):
            return {k: np.asarray([r[k] for r in items]) for k in keys}
    return items


def _exec_chain(source, ops: List[_Op]) -> Block:
    block = source() if callable(source) else source
    for op in ops:
        block = _apply_op(block, op)
    return block


class Dataset:
    """Lazy, immutable; every transform returns a new Dataset."""

    def __init__(self, partitions: List[Any], ops: Optional[List[_Op]] = None,
                 parallelism: int = DEFAULT_WINDOW):
        # partitions: read thunks (callables) or ObjectRefs of blocks
        self._partitions = partitions
        self._ops = ops or []
        self._parallelism = parallelism

    # ----------------------------------------------------------- transforms
    def _with_op(self, op: _Op) -> "Dataset":
        return Dataset(self._partitions, self._ops + [op], self._parallelism)

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    **_ignored) -> "Dataset":
        return self._with_op(_Op("map_batches", fn, batch_format=batch_format))

    def map(self, fn: Callable) -> "Dataset":
        return self._with_op(_Op("map", fn))

    def filter(self, fn: Callable) -> "Dataset":
        return self._with_op(_Op("filter", fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with_op(_Op("flat_map", fn))

    def union(self, *others: "Dataset") -> "Dataset":
        blocks = self._barrier_blocks()
        for o in others:
            blocks.extend(o._barrier_blocks())
        return Dataset(blocks, [], self._parallelism)

    def limit(self, n: int) -> "Dataset":
        out: List[Block] = []
        total = 0
        for block in self._stream_blocks():
            take = min(n - total, block_len(block))
            if take > 0:
                out.append(block_slice(block, 0, take))
                total += take
            if total >= n:
                break
        return Dataset(out, [], self._parallelism)

    def repartition(self, num_blocks: int) -> "Dataset":
        full = block_concat(list(self._stream_blocks()))
        n = block_len(full)
        sizes = [n // num_blocks + (1 if i < n % num_blocks else 0)
                 for i in range(num_blocks)]
        blocks, off = [], 0
        for s in sizes:
            blocks.append(block_slice(full, off, off + s))
            off += s
        return Dataset(blocks, [], self._parallelism)

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        n_parts = max(len(self._partitions), 1)
        full = block_concat(list(self._stream_blocks()))
        n = block_len(full)
        perm = np.random.default_rng(seed).permutation(n)
        if isinstance(full, dict):
            shuffled: Block = {k: v[perm] for k, v in full.items()}
        else:
            shuffled = [full[i] for i in perm]
        return Dataset([shuffled], [], self._parallelism).repartition(n_parts)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        full = block_concat(list(self._stream_blocks()))
        if isinstance(full, dict):
            order = np.argsort(full[key], kind="stable")
            if descending:
                order = order[::-1]
            return Dataset([{k: v[order] for k, v in full.items()}], [],
                           self._parallelism)
        items = sorted(full, key=lambda r: r[key], reverse=descending)
        return Dataset([items], [], self._parallelism)

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # ------------------------------------------------------------ execution
    def _stream_blocks(self) -> Iterator[Block]:
        """The streaming executor: fused per-block tasks, bounded window."""
        import ray_tpu

        if not self._partitions:
            return
        use_tasks = ray_tpu.is_initialized() and (
            len(self._partitions) > 1 or self._ops)
        if not use_tasks:
            for p in self._partitions:
                yield _exec_chain(p, self._ops)
            return

        exec_task = ray_tpu.remote(_exec_chain)
        window = self._parallelism
        pending: List[Any] = []
        idx = 0
        emitted = 0
        results: Dict[int, Any] = {}
        submitted = {}
        while emitted < len(self._partitions):
            while idx < len(self._partitions) and len(pending) < window:
                ref = exec_task.remote(self._partitions[idx], self._ops)
                submitted[ref] = idx
                pending.append(ref)
                idx += 1
            if not pending:
                break
            ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=300)
            for ref in ready:
                results[submitted[ref]] = ray_tpu.get(ref)
            # emit in order (deterministic iteration, like ordered execution)
            while emitted in results:
                yield results.pop(emitted)
                emitted += 1

    def _barrier_blocks(self) -> List[Block]:
        return list(self._stream_blocks())

    # ----------------------------------------------------------- consumers
    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        carry: Optional[Block] = None
        for block in self._stream_blocks():
            if carry is not None:
                block = block_concat([carry, block])
                carry = None
            off = 0
            n = block_len(block)
            while n - off >= batch_size:
                yield block_to_batch(block_slice(block, off, off + batch_size),
                                     batch_format)
                off += batch_size
            if off < n:
                carry = block_slice(block, off, n)
        if carry is not None and not drop_last:
            yield block_to_batch(carry, batch_format)

    def iter_rows(self) -> Iterator[Any]:
        for block in self._stream_blocks():
            yield from rows_of(block)

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(block_len(b) for b in self._stream_blocks())

    def schema(self) -> Optional[List[str]]:
        for block in self._stream_blocks():
            if isinstance(block, dict):
                return list(block)
            return None
        return None

    def materialize(self) -> "Dataset":
        return Dataset(self._barrier_blocks(), [], self._parallelism)

    def num_blocks(self) -> int:
        return len(self._partitions)

    # --------------------------------------------------------------- splits
    def split(self, n: int) -> List["Dataset"]:
        """Shard by partition round-robin (train ingest: one shard per
        worker; reference streaming_split)."""
        shards: List[List[Any]] = [[] for _ in range(n)]
        for i, p in enumerate(self._partitions):
            shards[i % n].append(p)
        return [Dataset(s, list(self._ops), self._parallelism) for s in shards]

    streaming_split = split

    # -------------------------------------------------------------- writers
    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow as pa
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self._stream_blocks()):
            table = block_to_batch(block, "pyarrow")
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))

    def __repr__(self):
        return (f"Dataset(num_blocks={len(self._partitions)}, "
                f"ops={[o.kind for o in self._ops]})")


class GroupedData:
    """Hash-partitioned groupby + aggregations (miniature hash_shuffle)."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _groups(self) -> Dict[Any, Block]:
        import collections

        groups: Dict[Any, List[Any]] = collections.defaultdict(list)
        for row in self._ds.iter_rows():
            groups[row[self._key]].append(row)
        return {k: _rows_to_block(v) for k, v in groups.items()}

    def _agg(self, col: str, fn: Callable, name: str) -> Dataset:
        rows = [{self._key: k, name: fn(np.asarray(block[col]))}
                for k, block in sorted(self._groups().items())]
        return Dataset([_rows_to_block(rows)])

    def count(self) -> Dataset:
        rows = [{self._key: k, "count": block_len(b)}
                for k, b in sorted(self._groups().items())]
        return Dataset([_rows_to_block(rows)])

    def sum(self, col: str) -> Dataset:
        return self._agg(col, np.sum, f"sum({col})")

    def mean(self, col: str) -> Dataset:
        return self._agg(col, np.mean, f"mean({col})")

    def min(self, col: str) -> Dataset:
        return self._agg(col, np.min, f"min({col})")

    def max(self, col: str) -> Dataset:
        return self._agg(col, np.max, f"max({col})")

    def map_groups(self, fn: Callable) -> Dataset:
        blocks = [batch_to_block(fn(block_to_batch(b, "numpy")))
                  for _, b in sorted(self._groups().items())]
        return Dataset(blocks)
