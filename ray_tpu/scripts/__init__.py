"""CLI package."""
