"""`ray-tpu` CLI: cluster bring-up, introspection, jobs, timeline.

Parity (core subset) with the reference CLI (`python/ray/scripts/scripts.py`:
`ray start/stop/status/list/summary/timeline/job`): head and worker-node
bring-up print a joinable address; every other subcommand attaches via
--address / RAY_TPU_ADDRESS.
"""

from __future__ import annotations

import argparse
import json
import os
from ray_tpu.core import config as _cfg
import signal
import subprocess
import sys
import time

from ray_tpu.utils.platform import STATE_DIR

ADDR_FILE = os.path.join(STATE_DIR, "last_address")


def _save_address(addr: str) -> None:
    os.makedirs(os.path.dirname(ADDR_FILE), exist_ok=True)
    with open(ADDR_FILE, "w") as f:
        f.write(addr)


def _resolve_address(args) -> str:
    addr = getattr(args, "address", None) or _cfg.get("address") or None
    if not addr and os.path.exists(ADDR_FILE):
        addr = open(ADDR_FILE).read().strip()
    if not addr:
        sys.exit("no cluster address: pass --address, set RAY_TPU_ADDRESS, "
                 "or run `ray-tpu start --head` on this machine first")
    return addr


def _connect(args):
    import ray_tpu

    ray_tpu.init(address=_resolve_address(args))
    from ray_tpu.core.api import _global_client

    return _global_client()


# ------------------------------------------------------------------- start
def cmd_start(args) -> None:
    if args.head:
        cmd = [sys.executable, "-m", "ray_tpu.core.head_main",
               "--session", f"cli{os.getpid()}{int(time.time())%100000}",
               "--port", str(args.port)]
        if args.num_cpus is not None:
            cmd += ["--num-cpus", str(args.num_cpus)]
        if args.num_tpu_chips is not None:
            cmd += ["--num-tpu-chips", str(args.num_tpu_chips)]
        if args.resources:
            cmd += ["--resources", args.resources]
        import tempfile

        fd, port_file = tempfile.mkstemp(prefix="ray_tpu_ports_")
        os.close(fd)
        os.unlink(port_file)   # head_main atomically re-creates it when ready
        cmd += ["--port-file", port_file]
        # fully detach stdio: a live head must not hold the CLI's pipes —
        # an inherited stdout OR stderr keeps `ray-tpu start --head | tee`
        # (and any capture_output caller, e.g. the cluster launcher's
        # command runner) waiting for EOF forever. stderr goes to a session
        # log file so head errors stay diagnosable.
        from ray_tpu.core.worker_logs import session_log_dir

        err_path = os.path.join(session_log_dir(cmd[cmd.index("--session") + 1]),
                                "head.err")
        with open(err_path, "ab") as errf:
            proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                                    stderr=errf, start_new_session=True)
        port = dash = None
        try:
            deadline = time.time() + 60
            while time.time() < deadline:
                if proc.poll() is not None:
                    sys.exit("head failed to start")
                if os.path.exists(port_file):
                    ports = json.load(open(port_file))
                    port, dash = ports["port"], ports.get("dashboard_port")
                    cproxy = ports.get("client_proxy_port")
                    break
                time.sleep(0.05)
        finally:
            try:
                os.unlink(port_file)
            except OSError:
                pass
        if port is None:
            proc.terminate()
            sys.exit("head failed to start (timeout)")
        addr = f"127.0.0.1:{port}"
        _save_address(addr)
        print(f"started head at {addr} (pid {proc.pid})")
        if dash:
            print(f"dashboard: http://127.0.0.1:{dash}")
        print(f"join with: ray-tpu start --address={addr}")
        print(f"drivers:   RAY_TPU_ADDRESS={addr} python my_script.py")
        if cproxy:
            print(f"remote drivers: ray_tpu.init("
                  f"address=\"ray-tpu://<this-host>:{cproxy}\")")
        if args.block:
            try:
                proc.wait()
            except KeyboardInterrupt:
                proc.terminate()
    else:
        if not args.address:
            sys.exit("worker nodes need --address=<head host:port>")
        cmd = [sys.executable, "-m", "ray_tpu.core.node_main",
               "--address", args.address]
        if args.num_cpus is not None:
            cmd += ["--num-cpus", str(args.num_cpus)]
        if args.num_tpu_chips is not None:
            cmd += ["--num-tpu-chips", str(args.num_tpu_chips)]
        if args.resources:
            cmd += ["--resources", args.resources]
        if args.labels:
            cmd += ["--labels", args.labels]
        # same detachment as the head branch: the daemon must not hold the
        # CLI's pipes or die with the terminal; stderr to a state-dir file
        os.makedirs(STATE_DIR, exist_ok=True)
        with open(os.path.join(STATE_DIR, "node_daemon.err"), "ab") as errf:
            proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                                    stderr=errf, start_new_session=True)
        print(f"node daemon started (pid {proc.pid}), joined {args.address}")
        if args.block:
            try:
                proc.wait()
            except KeyboardInterrupt:
                proc.terminate()


def cmd_stop(args) -> None:
    import glob

    n = 0
    for pat in ("ray_tpu.core.head_main", "ray_tpu.core.node_main",
                "ray_tpu.core.worker_main"):
        out = subprocess.run(["pkill", "-f", pat], capture_output=True)
        n += out.returncode == 0
    for seg in glob.glob("/dev/shm/rtpu_*"):
        try:
            os.unlink(seg)
        except OSError:
            pass
    if os.path.exists(ADDR_FILE):
        os.unlink(ADDR_FILE)
    print("stopped" if n else "nothing to stop")


# ------------------------------------------------------------------ status
def cmd_status(args) -> None:
    client = _connect(args)
    info = client.head_request("cluster_info")
    print(f"session:  {info['session']}")
    print(f"uptime:   {info['uptime']:.0f}s")
    print(f"nodes:    {info['num_nodes']}  workers: {info['num_workers']}")
    if info.get("dashboard_port"):
        print(f"dashboard: http://127.0.0.1:{info['dashboard_port']}")
    print("resources:")
    for r, total in sorted(info["total_resources"].items()):
        avail = info["available_resources"].get(r, 0)
        print(f"  {r}: {avail:g}/{total:g} available")


def cmd_list(args) -> None:
    client = _connect(args)
    kind = {"pgs": "placement_groups"}.get(args.kind, args.kind)
    rows = client.head_request("list_state", kind=kind)
    print(json.dumps(rows[:args.limit] if args.limit else rows, indent=2,
                     default=str))


def cmd_summary(args) -> None:
    _connect(args)
    from ray_tpu.util import state

    print(json.dumps({"tasks": state.summarize_tasks(),
                      "actors": state.summarize_actors(),
                      "objects": state.summarize_objects()}, indent=2))


def cmd_timeline(args) -> None:
    _connect(args)
    import ray_tpu

    events = ray_tpu.timeline(args.output, format=args.format)
    print(f"wrote {len(events)} trace events to {args.output}")


# --------------------------------------------------------------------- job
def cmd_job(args) -> None:
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    if args.job_cmd == "submit":
        parts = args.entrypoint
        if parts and parts[0] == "--":   # `job submit -- python x.py`
            parts = parts[1:]
        entrypoint = " ".join(parts)
        job_id = client.submit_job(entrypoint=entrypoint)
        print(f"submitted {job_id}")
        if not args.no_wait:
            status = client.wait_until_finished(job_id, timeout=args.timeout)
            print(f"status: {status}")
            print(client.get_job_logs(job_id), end="")
            if status != "SUCCEEDED":
                sys.exit(1)
    elif args.job_cmd == "status":
        print(client.get_job_status(args.job_id))
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.job_id), end="")
    elif args.job_cmd == "list":
        print(json.dumps(client.list_jobs(), indent=2))
    elif args.job_cmd == "stop":
        print("stopped" if client.stop_job(args.job_id) else "not running")


def cmd_stack(args) -> None:
    """Live thread stacks of cluster processes (reference `ray stack`,
    done cooperatively instead of via py-spy/ptrace)."""
    client = _connect(args)
    rows = client.head_request("list_state", kind="workers")
    if args.worker:
        rows = [w for w in rows if w["worker_id"].startswith(args.worker)]
        if not rows:
            sys.exit(f"no worker with id prefix {args.worker!r}")
    for w in rows:
        print(f"===== worker {w['worker_id'][:12]} pid={w['pid']} "
              f"{'driver' if w['is_driver'] else 'worker'}"
              f"{' actor=' + w['actor'][:12] if w.get('actor') else ''}")
        text = client.head_request("worker_stacks",
                                   worker_id=bytes.fromhex(w["worker_id"]))
        print(text or "<unreachable>")


def cmd_config(args) -> None:
    """The running head's full flag table (reference `ray_config_def.h`
    introspection): value, default, and where each value came from."""
    if args.local:
        from ray_tpu.core import config as cfg

        rows = cfg.dump()
    else:
        rows = _connect(args).head_request("get_config")
    if args.json:
        print(json.dumps(rows, indent=1, default=str))
        return
    w = max(len(r["name"]) for r in rows)
    for r in rows:
        mark = " [negotiated]" if r["negotiated"] else ""
        star = "" if r["source"] == "default" else f"  ({r['source']})"
        print(f"{r['name']:<{w}}  {r['value']!r:<14}{star}{mark}")
        if args.verbose:
            print(f"{'':<{w}}  env={r['env']} default={r['default']!r}")
            print(f"{'':<{w}}  {r['doc']}")


def cmd_up(args) -> None:
    from ray_tpu.autoscaler import launcher

    cfg = launcher.load_config(args.config_file)
    state = launcher.up(cfg)
    print(f"cluster {state['cluster_name']!r} is up at {state['address']}")
    print(f"  attach:  ray-tpu attach {state['cluster_name']}")
    print(f"  exec:    ray-tpu exec {state['cluster_name']} -- <cmd>")
    print(f"  down:    ray-tpu down {state['cluster_name']}")


def cmd_down(args) -> None:
    from ray_tpu.autoscaler import launcher

    target = args.cluster
    if target.endswith((".yaml", ".yml")):
        target = launcher.load_config(target)["cluster_name"]
    launcher.down(target)


def cmd_exec(args) -> None:
    from ray_tpu.autoscaler import launcher

    parts = args.command
    if parts and parts[0] == "--":
        parts = parts[1:]
    rc = launcher.exec_cmd(args.cluster, " ".join(parts), on=args.node)
    sys.exit(rc)


def cmd_attach(args) -> None:
    from ray_tpu.autoscaler import launcher

    argv = launcher.attach_argv(args.cluster)
    os.execvp(argv[0], argv)


def cmd_rsync(args) -> None:
    from ray_tpu.autoscaler import launcher

    launcher.rsync(args.cluster, args.source, args.target,
                   up_=args.rsync_cmd == "rsync-up")
    print("done")


def cmd_logs(args) -> None:
    """Worker log access (reference `ray logs`): list the session's log
    files, or print one (`ray-tpu logs worker-<tag>.err --tail 50`).
    `--worker <worker_id>` resolves a live/recent worker's files."""
    client = _connect(args)
    target = args.filename
    if args.worker:
        rows = client.head_request("list_state", kind="workers")
        match = [w for w in rows
                 if w["worker_id"].startswith(args.worker) and w.get("log_tag")]
        if not match:
            sys.exit(f"no worker with id prefix {args.worker!r} "
                     f"(or it has no captured logs)")
        target = f"worker-{match[0]['log_tag']}.{args.stream}"
    if not target:
        for row in client.head_request("list_logs"):
            size = row["size"]
            print(f"{'?' if size is None else size:>10}  {row['file']}")
        return
    lines = client.head_request("get_log", filename=target, tail=args.tail)
    if lines is None:
        sys.exit(f"no such log file: {target}")
    for line in lines:
        print(line)


def _fetch_hotpath(client) -> dict:
    from urllib.request import urlopen

    port = client.head_request("cluster_info").get("dashboard_port")
    if not port:
        sys.exit("the head has no dashboard (hotpath API unavailable)")
    with urlopen(f"http://127.0.0.1:{port}/api/hotpath", timeout=5) as r:
        return json.load(r)


def _render_hotpath(hp: dict, now: float) -> str:
    """One frame of the `ray-tpu top` screen from an /api/hotpath poll:
    per-plane golden signals — ring occupancy with writer/reader stall
    attribution (a writer stall means the READER is the bottleneck and
    vice versa), compiled-chain health, timed fused-step phases — and
    the watchdog's recent hotpath_regression flags. Pure so tests can
    render a canned payload."""
    out = []

    def sect(title, rows, fmt):
        out.append(title)
        if not rows:
            out.append("  (none)")
            return
        for r in rows:
            out.append("  " + fmt(r))

    def age(r):
        return f"{max(now - r.get('ts', now), 0.0):4.1f}s"

    sect("rings (occupancy + stall attribution)",
         sorted(hp.get("rings") or [], key=lambda r: str(r.get("key"))),
         lambda r: (lambda s: (
             f"{r.get('key', '?'):<40} {s.get('plane', '?'):<12} "
             f"occ {s.get('occupancy', 0)}/{s.get('depth', 0)} "
             f"x{s.get('lanes', 1)}  "
             f"wstall {s.get('writer_stall_s', 0.0):8.3f}s  "
             f"rstall {s.get('reader_stall_s', 0.0):8.3f}s  "
             f"w/r {s.get('writes', 0)}/{s.get('reads', 0)}  "
             + ("reader-bound" if s.get("writer_stall_s", 0.0)
                > s.get("reader_stall_s", 0.0) else "writer-bound")
             + f"  [{age(r)}]"))(r.get("stats") or {}))
    sect("compiled chains",
         sorted(hp.get("chains") or [], key=lambda r: str(r.get("key"))),
         lambda r: (lambda s: (
             f"{r.get('key', '?'):<40} gen {s.get('generation', 0)} "
             f"compiled {s.get('compiled', 0)} "
             f"fallback {s.get('dynamic_fallback', 0)} "
             f"fenced {s.get('fenced', 0)} "
             f"p99 {s.get('p99_s') if s.get('p99_s') is not None else '-'}s"
             f"  [{age(r)}]"))(r.get("stats") or {}))
    sect("proxy ingress chains (compiled serving to the wire)",
         sorted(hp.get("proxy_chains") or [], key=lambda r: str(r.get("key"))),
         lambda r: (lambda s: (
             f"{r.get('key', '?'):<40} gen {s.get('generation', 0)} "
             f"compiled {s.get('compiled', 0)} "
             f"fallback {s.get('dynamic_fallback', 0)} "
             f"fenced {s.get('fenced', 0)} "
             f"p99 {s.get('p99_s') if s.get('p99_s') is not None else '-'}s"
             f"  [{age(r)}]"))(r.get("stats") or {}))
    sect("train phases (timed step, per rank)",
         sorted(hp.get("train_phases") or [],
                key=lambda r: str(r.get("key"))),
         lambda r: (lambda s: (
             f"{r.get('key', '?'):<40} step {s.get('step_s', 0.0):7.4f}s  "
             + "  ".join(f"{k[:-2]} {v:7.4f}s"
                         for k, v in sorted(s.items())
                         if k.endswith("_s") and k != "step_s")
             + f"  [{age(r)}]"))(r.get("stats") or {}))
    sect("hotpath regressions (watchdog)",
         (hp.get("anomalies") or [])[-10:],
         lambda a: (f"{a.get('metric', '?'):<22} "
                    + " ".join(f"{k}={v}" for k, v in sorted(a.items())
                               if k not in ("ts", "kind", "anomaly",
                                            "metric") and v is not None)))
    sect("fence/failover events",
         (hp.get("fence_events") or [])[-10:],
         lambda e: (f"{e.get('kind', '?'):<16} "
                    f"chain {e.get('chain', '?')} "
                    + " ".join(f"{k}={v}" for k, v in sorted(e.items())
                               if k not in ("ts", "kind", "chain")
                               and v is not None)))
    return "\n".join(out)


def cmd_top(args) -> None:
    """`ray-tpu top`: live per-plane golden signals of the compiled hot
    paths from `GET /api/hotpath` — refreshed in place like `top`, or a
    single frame with --once (scripts/tests)."""
    client = _connect(args)
    while True:
        frame = _render_hotpath(_fetch_hotpath(client), time.time())
        if args.once:
            print(frame)
            return
        sys.stdout.write("\x1b[2J\x1b[H"
                         + time.strftime("ray-tpu top  %H:%M:%S\n\n")
                         + frame + "\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return


def cmd_serve(args) -> None:
    _connect(args)
    from ray_tpu import serve as serve_api

    if args.serve_cmd == "deploy":
        from ray_tpu.serve.build_app import deploy_config_file

        names = deploy_config_file(args.config_file)
        port = serve_api.start()
        print(f"deployed {', '.join(names)}; http on 127.0.0.1:{port}")
    elif args.serve_cmd == "status":
        print(json.dumps(serve_api.status(), indent=2, default=str))
    elif args.serve_cmd == "shutdown":
        serve_api.shutdown()
        print("serve shut down")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ray-tpu",
                                description="TPU-native distributed runtime")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head or worker node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", default=None)
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpu-chips", type=int, default=None)
    sp.add_argument("--resources", default=None, help="JSON dict")
    sp.add_argument("--labels", default=None, help="JSON dict (worker nodes)")
    sp.add_argument("--block", action="store_true")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop all local cluster processes")
    sp.set_defaults(fn=cmd_stop)

    for name, fn in (("status", cmd_status), ("summary", cmd_summary)):
        sp = sub.add_parser(name)
        sp.add_argument("--address", default=None)
        sp.set_defaults(fn=fn)

    sp = sub.add_parser("list", help="list cluster state")
    sp.add_argument("kind", choices=["tasks", "task_events", "actors",
                                     "workers", "objects", "nodes", "pgs"])
    sp.add_argument("--limit", type=int, default=None)
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("timeline", help="dump a Chrome trace")
    sp.add_argument("--output", default="/tmp/ray_tpu_timeline.json")
    sp.add_argument("--format", default=None, choices=["chrome"],
                    help="'chrome' writes the Trace Event Object envelope "
                         "(Perfetto-loadable) incl. cross-process workload "
                         "spans; default is the legacy bare array")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("top", help="live compiled-plane golden signals "
                                    "(/api/hotpath)")
    sp.add_argument("--address", default=None)
    sp.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    sp.add_argument("--interval", type=float, default=2.0)
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser("stack", help="dump live thread stacks of workers")
    sp.add_argument("--worker", default=None, help="worker id hex prefix")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser("config", help="show the cluster's config flags")
    sp.add_argument("--address", default=None)
    sp.add_argument("--local", action="store_true",
                    help="this process's view instead of the head's")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--verbose", "-v", action="store_true")
    sp.set_defaults(fn=cmd_config)

    sp = sub.add_parser("up", help="bring a cluster up from cluster.yaml")
    sp.add_argument("config_file")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear a launched cluster down")
    sp.add_argument("cluster", help="cluster name or its cluster.yaml")
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("exec", help="run a command on a cluster node")
    sp.add_argument("cluster")
    sp.add_argument("--node", default="head",
                    help='"head" or a worker index')
    sp.add_argument("command", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_exec)

    sp = sub.add_parser("attach", help="interactive shell on the head")
    sp.add_argument("cluster")
    sp.set_defaults(fn=cmd_attach)

    for name in ("rsync-up", "rsync-down"):
        sp = sub.add_parser(name)
        sp.add_argument("cluster")
        sp.add_argument("source")
        sp.add_argument("target")
        sp.set_defaults(fn=cmd_rsync, rsync_cmd=name)

    sp = sub.add_parser("logs", help="list or print worker log files")
    sp.add_argument("filename", nargs="?", default=None,
                    help="log file name (omit to list)")
    sp.add_argument("--worker", default=None,
                    help="worker id (hex prefix) instead of a filename")
    sp.add_argument("--stream", choices=["out", "err"], default="out",
                    help="which stream with --worker")
    sp.add_argument("--tail", type=int, default=None)
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("serve")
    ssub = sp.add_subparsers(dest="serve_cmd", required=True)
    sd = ssub.add_parser("deploy")
    sd.add_argument("config_file")
    sd.add_argument("--address", default=None)
    sd.set_defaults(fn=cmd_serve)
    for name in ("status", "shutdown"):
        sd = ssub.add_parser(name)
        sd.add_argument("--address", default=None)
        sd.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("job")
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--address", default=None)
    j.add_argument("--no-wait", action="store_true")
    j.add_argument("--timeout", type=float, default=600.0)
    j.add_argument("entrypoint", nargs=argparse.REMAINDER)
    j.set_defaults(fn=cmd_job)
    for name in ("status", "logs", "stop"):
        j = jsub.add_parser(name)
        j.add_argument("job_id")
        j.add_argument("--address", default=None)
        j.set_defaults(fn=cmd_job)
    j = jsub.add_parser("list")
    j.add_argument("--address", default=None)
    j.set_defaults(fn=cmd_job)
    return p


def main(argv=None) -> None:
    # die quietly when downstream of a closed pipe (`ray-tpu list ... | head`)
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
