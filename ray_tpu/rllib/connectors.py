"""Connector pipelines: pluggable observation/action transforms.

Parity: `rllib/connectors/` (env-to-module, module-to-env pipelines) —
composable, stateful transforms sitting between the environment and the
RLModule, owned by the env runner so preprocessing travels WITH the
policy (checkpointable state, e.g. running obs statistics).

- EnvToModule connectors map raw env observations -> module inputs
  (normalize, clip, frame-stack).
- ModuleToEnv connectors map module actions -> env actions (already
  handled structurally by action_scale; connectors add clipping etc.).

Wired via `AlgorithmConfig.env_runners(env_to_module_connector=...)`:
the callable builds a pipeline per runner (reference's
`config.env_to_module_connector` factory contract).
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np


class Connector:
    """One transform. `__call__(batch)` maps a [N, ...] numpy batch."""

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, batch: np.ndarray) -> np.ndarray:
        """Apply WITHOUT mutating connector state — for out-of-band
        inputs (bootstrap values at truncations, the trailing value
        step) that must see the same normalization as policy inputs but
        must not advance running statistics/history."""
        return self(batch)

    # connectors may carry state that must checkpoint with the runner
    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass


class ConnectorPipeline(Connector):
    def __init__(self, connectors: Optional[List[Connector]] = None):
        self.connectors = list(connectors or [])

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def __call__(self, batch):
        for c in self.connectors:
            batch = c(batch)
        return batch

    def transform(self, batch):
        for c in self.connectors:
            batch = c.transform(batch)
        return batch

    def get_state(self) -> dict:
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: dict) -> None:
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, batch):
        return np.clip(batch, self.low, self.high)


class MeanStdObs(Connector):
    """Running mean/std observation normalization (reference
    MeanStdFilter connector) — Welford accumulation over every batch
    that flows through; state checkpoints with the runner."""

    def __init__(self, eps: float = 1e-8, update: bool = True):
        self.count = 0.0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None
        self.eps = eps
        self.update = update

    def __call__(self, batch):
        b = np.asarray(batch, np.float64)
        if self.update:
            n = b.shape[0]
            bmean = b.mean(0)
            bvar = b.var(0)
            if self.mean is None:
                self.mean = bmean
                self.m2 = bvar * n
                self.count = n
            else:
                delta = bmean - self.mean
                tot = self.count + n
                self.mean = self.mean + delta * n / tot
                self.m2 = (self.m2 + bvar * n
                           + delta ** 2 * self.count * n / tot)
                self.count = tot
        if self.mean is None:
            return batch
        std = np.sqrt(self.m2 / max(self.count, 1.0)) + self.eps
        return ((b - self.mean) / std).astype(np.float32)

    def transform(self, batch):
        if self.mean is None:
            return batch
        b = np.asarray(batch, np.float64)
        std = np.sqrt(self.m2 / max(self.count, 1.0)) + self.eps
        return ((b - self.mean) / std).astype(np.float32)

    def get_state(self) -> dict:
        return {"count": self.count,
                "mean": None if self.mean is None else self.mean.copy(),
                "m2": None if self.m2 is None else self.m2.copy()}

    def set_state(self, state: dict) -> None:
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]


class FrameStackObs(Connector):
    """Stack the last K observations along the feature axis (reference
    FrameStacking connector; flat-obs variant)."""

    def __init__(self, k: int = 4):
        self.k = k
        self._hist: List[np.ndarray] = []

    def __call__(self, batch):
        b = np.asarray(batch, np.float32)
        self._hist.append(b)
        while len(self._hist) < self.k:
            self._hist.insert(0, np.zeros_like(b))
        self._hist = self._hist[-self.k:]
        return np.concatenate(self._hist, axis=-1)

    def transform(self, batch):
        b = np.asarray(batch, np.float32)
        hist = (self._hist[1:] if len(self._hist) >= self.k
                else self._hist)[:]
        hist.append(b)
        while len(hist) < self.k:
            hist.insert(0, np.zeros_like(b))
        return np.concatenate(hist[-self.k:], axis=-1)

    def get_state(self) -> dict:
        return {"hist": [h.copy() for h in self._hist]}

    def set_state(self, state: dict) -> None:
        self._hist = [np.asarray(h) for h in state["hist"]]


class ClipActions(Connector):
    """module-to-env: clip continuous actions to the env bounds."""

    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, batch):
        return np.clip(batch, self.low, self.high)


def build_pipeline(spec: Any) -> Optional[ConnectorPipeline]:
    """Factory contract: spec is None | Connector | list[Connector] |
    callable() -> any of those (the reference passes factories so each
    runner gets its OWN stateful pipeline)."""
    if spec is None:
        return None
    if callable(spec) and not isinstance(spec, Connector):
        spec = spec()
    if spec is None:
        return None
    if isinstance(spec, ConnectorPipeline):
        return spec
    if isinstance(spec, Connector):
        return ConnectorPipeline([spec])
    return ConnectorPipeline(list(spec))
