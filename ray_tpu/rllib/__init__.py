"""ray_tpu.rllib — JAX-native reinforcement learning library.

Capability parity with the reference's RLlib (`rllib/` — Algorithm/
AlgorithmConfig, EnvRunnerGroup actor rollouts, Learner updates): rollouts
run on CPU env-runner actors; the learner update is a single jitted JAX
function, optionally sharded over a device-mesh dp axis (XLA psum over ICI
replaces the reference's torch-DDP learner group).
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.algorithms.iql import IQL, IQLConfig
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.env.envs import (Box, CartPole, Discrete, Env, Pendulum,
                                    VectorEnv, make_env, register_env)
from ray_tpu.rllib.env.multi_agent import (MultiAgentEnv, MultiAgentEnvRunner,
                                           TargetMatch)
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rllib.core.rl_module import ModuleSpec, RLModule, spec_from_env

__all__ = [
    "Algorithm", "AlgorithmConfig", "BC", "BCConfig", "PPO", "PPOConfig", "DQN", "DQNConfig",
    "SAC", "SACConfig", "IMPALA", "IMPALAConfig", "APPO", "APPOConfig",
    "MARWIL", "MARWILConfig", "CQL", "CQLConfig", "IQL", "IQLConfig", "DreamerV3", "DreamerV3Config",
    "Box", "CartPole", "Discrete", "Env", "Pendulum",
    "VectorEnv", "make_env", "register_env", "SingleAgentEnvRunner",
    "MultiAgentEnv", "MultiAgentEnvRunner", "TargetMatch",
    "EnvRunnerGroup", "ModuleSpec", "RLModule", "spec_from_env",
]
